"""In-worker jax helpers for JaxTrainer loops.

Role-equivalent of python/ray/train/torch/train_loop_utils.py ::
prepare_model / prepare_data_loader, TPU-first: instead of wrapping a model
in DDP, we build the device mesh, place params with NamedSharding, and sync
gradients — in-jit (psum over ICI, the "xla" path) or eagerly through the
collective group (the "ring" CPU twin).
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np


def build_mesh(axes: dict[str, int] | None = None, topology=None):
    """Mesh over THIS jax runtime's devices. On a real multi-host gang
    (jax.distributed initialized) that is the whole slice; on the ring
    backend it is the process-local devices. axes={} → 1-D "dp" mesh.

    With ``topology`` (a parallel.topology.SliceTopology), the mesh
    composes cross-slice DCN axes with in-slice ICI axes — the
    multi-slice layout (JaxTrainer's ``topology=`` lands here)."""
    import jax
    from ray_tpu.parallel.mesh import MeshSpec

    if topology is not None:
        return topology.build_mesh()
    devices = jax.devices()
    if not axes:
        axes = {"dp": len(devices)}
    return MeshSpec(dict(axes)).build(devices)


def shard_params(params: Any, mesh, logical_dims: Any = None):
    """Place a param pytree onto the mesh. With logical_dims (see
    parallel.mesh.LogicalRules), params get TP/FSDP shardings; without,
    they are replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_tpu.parallel.mesh import LogicalRules

    if logical_dims is not None:
        shardings = LogicalRules().tree_shardings(logical_dims, mesh)
        return jax.device_put(params, shardings)
    return jax.device_put(
        params, jax.tree.map(lambda _: NamedSharding(mesh, P()), params)
    )


def _flatten_tree(grads: Any):
    """(leaves, treedef, flat f32 vector) for a grad pytree."""
    import jax

    leaves, treedef = jax.tree.flatten(grads)
    flat = np.concatenate([np.asarray(x, np.float32).ravel() for x in leaves])
    return leaves, treedef, flat


def _unflatten_tree(flat: np.ndarray, leaves, treedef) -> Any:
    """Inverse of :func:`_flatten_tree`, restoring leaf shapes/dtypes."""
    import jax

    out, offset = [], 0
    for leaf in leaves:
        size = int(np.prod(np.shape(leaf))) or 1
        out.append(
            flat[offset : offset + size].reshape(np.shape(leaf)).astype(
                np.asarray(leaf).dtype
            )
        )
        offset += size
    return jax.tree.unflatten(treedef, out)


def sync_gradients(grads: Any, group_name: str) -> Any:
    """Eager cross-worker gradient mean for the ring backend. (On the xla
    backend gradients sync in-jit via psum — never call this there.)

    Quantized wire compression is transparent here: it lives in the
    group's CollectiveConfig (ScalingConfig.collective_config), not in
    the call site."""
    from ray_tpu.util.collective import collective

    group = collective.get_group(group_name)
    if group.world_size == 1:
        return grads
    leaves, treedef, flat = _flatten_tree(grads)
    flat = np.asarray(group.allreduce(flat)) / group.world_size
    return _unflatten_tree(flat, leaves, treedef)


def sync_gradients_sharded(
    per_device_grads: list, group_name: str
) -> Any:
    """Two-tier gradient mean for hierarchical-backend gangs: one grad
    pytree PER LOCAL DEVICE in, the globally-averaged pytree out.

    Tier 1 reduces the local shards in one jit (psum over ICI); tier 2
    rides the DCN ring with this group's CollectiveConfig (so int8/fp8
    wire compression applies only to the cross-host hop). Falls back to
    host-mean + :func:`sync_gradients` on non-hierarchical groups."""
    from ray_tpu.util.collective import collective

    group = collective.get_group(group_name)
    flats = []
    leaves = treedef = None
    for grads in per_device_grads:
        leaves, treedef, flat = _flatten_tree(grads)
        flats.append(flat)
    n_local = len(flats)
    denom = group.world_size * n_local
    if not hasattr(group, "allreduce_sharded"):
        total = np.sum(np.stack(flats), axis=0)
        if group.world_size > 1:
            total = np.asarray(group.allreduce(total))
        return _unflatten_tree(total / denom, leaves, treedef)
    flat = np.asarray(group.allreduce_sharded(flats)) / denom
    return _unflatten_tree(flat, leaves, treedef)


def grad_psum(x, axis: str = "dp", topology=None):
    """The default in-jit gradient reduce (use inside shard_map/jit).

    Single-slice meshes psum over ``axis``; with a SliceTopology the
    reduce is placed tier by tier via ``hierarchical_psum`` — ICI first,
    then DCN — so the compiler never routes a collective-heavy reduce
    over the slow tier. build_mesh(topology=...) callers pass the same
    topology here to get the matching reduction order."""
    import jax

    if topology is not None:
        return topology.hierarchical_psum(x)
    return jax.lax.psum(x, axis)


def shard_batch(batch: Any, mesh, axis: str = "dp"):
    """device_put a host batch with batch-dim sharding over `axis`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)


def iter_global_batches(
    it: Iterable, *, world_rank: int, world_size: int
) -> Iterator:
    """Stride an iterable of batches across ranks (the ring-backend data
    path; ray_tpu.data shards upstream instead)."""
    for i, batch in enumerate(it):
        if i % world_size == world_rank:
            yield batch

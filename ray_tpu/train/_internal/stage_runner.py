"""MPMD pipeline-stage runner — 1F1B across slice gangs (ISSUE 10).

Each pipeline stage is a SEPARATE program on its own gang worker (MPMD:
"Scaling Deep Learning Training with MPMD Pipeline Parallelism"), holding
one contiguous slice of the model's layers. The driver-visible contract is
unchanged — workers run an ordinary train loop and ``report()`` per step —
but inside the step this runner executes the per-stage op stream from
``parallel.pipeline.schedule_1f1b``, handing activations (forward) and
activation-cotangents (backward) to neighbor stages over the collective
p2p plane. p2p is ALWAYS exact wire: ISSUE-7 quantization applies to
allreduce only, never to the activations the next stage's math depends on.

Inside a stage, dp/fsdp/tp still apply: the stage's params are sharded
over the worker's local GSPMD mesh with the same logical-dim rules the
non-pipelined path uses — pp composes with the other axes.

Memory follows the 1F1B bound (≤ num_stages − stage in-flight
microbatches) and backward recomputes the stage forward from the saved
INPUT (full per-stage remat) instead of holding vjp residuals — the
standard MPMD trade: activations-in-flight stay O(microbatch), at one
extra forward of FLOPs per microbatch.

Stage-level StepStats: wall time spent blocked in ``recv`` is attributed
to the ``pp_bubble`` phase, so the flight recorder's per-step breakdown
separates schedule bubbles from real compute and the release gate can
assert bubble ≤ its bound.

Checkpointing under pp > 1 is deliberately per-stage-local for now: the
committed-checkpoint reshard protocol covers (dp, fsdp, tp); resharding
across DIFFERENT pipeline factorizations requires merging stage trees
through models.transformer.merge_stages on rank 0 first (see
docs/sharding.md, "Pipeline stages and checkpoints").
"""

from __future__ import annotations

import time
from typing import Any, Callable

import numpy as np

from ray_tpu.train._internal import step_stats


class PipelineStageRunner:
    """Runs ONE stage's half of the 1F1B schedule, step by step.

    Parameters
    ----------
    stage_fn : (stage_params, activations) -> activations
        This stage's forward for interior/first stages (first stage
        receives the microbatch's model inputs instead of activations).
    last_stage_fn : (stage_params, activations, microbatch) -> scalar loss
        Used when this worker IS the last stage; closes over targets.
    params : pytree
        This stage's (possibly GSPMD-sharded) parameters.
    optimizer : optax-like GradientTransformation.
    activation_like : (microbatch) -> jax.ShapeDtypeStruct
        Wire shape/dtype of one microbatch's activations — recv needs it
        to allocate the buffer (the p2p plane is untyped bytes).
    microbatch_fn : (batch, index, count) -> microbatch
        Slices microbatch ``index`` of ``count`` out of a global batch.
    """

    def __init__(
        self,
        *,
        ctx: Any,
        stage_fn: Callable,
        last_stage_fn: Callable,
        params: Any,
        optimizer: Any,
        activation_like: Callable,
        microbatch_fn: Callable,
        param_shardings: Any = None,
        recv_timeout_s: float = 120.0,
    ):
        import jax

        from ray_tpu.parallel.pipeline import schedule_1f1b
        from ray_tpu.util.collective import collective

        pipe = ctx.pipeline
        if not pipe:
            raise ValueError(
                "PipelineStageRunner needs ScalingConfig.pipeline_stages > 1 "
                "(TrainContext.pipeline is unset)"
            )
        self.stage = int(pipe["stage"])
        self.num_stages = int(pipe["num_stages"])
        self.microbatches = int(pipe["microbatches"])
        if ctx.world_size != self.num_stages:
            raise NotImplementedError(
                "stage gangs wider than one worker are not wired yet: "
                f"world_size={ctx.world_size} != "
                f"pipeline_stages={self.num_stages}"
            )
        self.first = self.stage == 0
        self.last = self.stage == self.num_stages - 1
        self.group = collective.get_group(ctx.collective_group)
        self.params = params
        self.opt_state = optimizer.init(params)
        self.optimizer = optimizer
        self.activation_like = activation_like
        self.microbatch_fn = microbatch_fn
        self.recv_timeout_s = float(recv_timeout_s)
        self.schedule = schedule_1f1b(
            self.num_stages, self.microbatches, self.stage
        )

        self._fwd = jax.jit(stage_fn)

        def _bwd(p, a, ct):
            # Recompute-forward backward: vjp INSIDE jit so residuals
            # never outlive the call (the 1F1B memory bound holds on
            # stashed inputs, not activation stacks).
            _, vjp_fn = jax.vjp(stage_fn, p, a)
            return vjp_fn(ct)

        self._bwd = jax.jit(_bwd)
        self._last_grad = jax.jit(
            jax.value_and_grad(last_stage_fn, argnums=(0, 1))
        )

        def _apply(p, o, g):
            updates, new_o = self.optimizer.update(g, o, p)
            new_p = jax.tree.map(
                lambda w, u: w + u.astype(w.dtype), p, updates
            )
            return new_p, new_o

        self._apply = jax.jit(_apply, donate_argnums=(0, 1))
        self._param_shardings = param_shardings
        self._step_counter = 0

    # -- p2p plumbing -----------------------------------------------------
    def _recv(self, src: int, tag: str, like):
        """Blocking neighbor recv; blocked wall time IS the pipeline
        bubble at this stage, so it lands in the pp_bubble phase."""
        t0 = time.perf_counter()
        out = self.group.recv(
            src, tag=tag, timeout=self.recv_timeout_s, like=like
        )
        step_stats.record_phase("pp_bubble", time.perf_counter() - t0)
        return out

    def _send(self, array, dst: int, tag: str) -> None:
        self.group.send(np.asarray(array), dst, tag=tag)  # rtlint: disable=host-sync-in-step - eager p2p hand-off IS the wire, not an accidental sync

    # -- one optimizer step ----------------------------------------------
    def train_step(self, batch: Any) -> float:
        """Run this stage's full 1F1B op stream for one global batch and
        apply the stage-local optimizer update. Every stage returns the
        SAME mean microbatch loss (broadcast from the last stage)."""
        import jax

        grads_acc = None
        losses: list = []
        stash: dict[int, Any] = {}  # microbatch -> stage input (for bwd)
        step_tag = self._next_tag()
        for op, m in self.schedule:
            micro = self.microbatch_fn(batch, m, self.microbatches)
            if op == "F":
                if self.first:
                    a_in = self._model_inputs(micro)
                else:
                    a_in = self._recv(
                        self.stage - 1,
                        f"{step_tag}f{m}",
                        self.activation_like(micro),
                    )
                stash[m] = a_in
                if self.last:
                    # Last stage has no downstream cotangent to wait on:
                    # loss + grads come from one fused value_and_grad.
                    loss, (dp, da) = self._last_grad(
                        self.params, a_in, micro
                    )
                    losses.append(loss)
                    stash[m] = (dp, da)
                else:
                    y = self._fwd(self.params, a_in)
                    self._send(y, self.stage + 1, f"{step_tag}f{m}")
            else:  # "B"
                if self.last:
                    dp, da = stash.pop(m)
                else:
                    ct = self._recv(
                        self.stage + 1,
                        f"{step_tag}b{m}",
                        self.activation_like(micro),
                    )
                    dp, da = self._bwd(self.params, stash.pop(m), ct)
                if not self.first:
                    self._send(da, self.stage - 1, f"{step_tag}b{m}")
                grads_acc = (
                    dp
                    if grads_acc is None
                    else jax.tree.map(jax.numpy.add, grads_acc, dp)
                )
        grads = jax.tree.map(
            lambda g: g / self.microbatches, grads_acc
        )
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, grads
        )
        if self.last:
            local = float(np.mean([np.asarray(l) for l in losses]))  # rtlint: disable=host-sync-in-step - loss leaves the device to ride the broadcast wire
        else:
            local = 0.0
        loss = self.group.broadcast(
            np.asarray([local], np.float32),  # rtlint: disable=host-sync-in-step - the broadcast wire carries host arrays by design
            src_rank=self.num_stages - 1,
        )
        return float(loss[0])  # rtlint: disable=host-sync-in-step - per-step loss is the report-path scalar every stage returns

    def _model_inputs(self, micro: Any) -> Any:
        """What the first stage feeds its forward: the microbatch's
        inputs. Dict batches use 'x'/'inputs'; arrays pass through."""
        if isinstance(micro, dict):
            for key in ("x", "inputs", "tokens"):
                if key in micro:
                    return micro[key]
            raise KeyError(
                "first-stage microbatch dict needs an 'x'/'inputs'/'tokens' "
                "entry"
            )
        return micro

    def _next_tag(self) -> str:
        # Per-step tag namespace: microbatch m of step k must never pair
        # with microbatch m of step k±1 on a fast/slow neighbor pair.
        # Per-INSTANCE counter: every stage calls train_step once per
        # global step, so instance counters advance in lockstep across
        # the gang (a shared/class counter would not).
        self._step_counter += 1
        return f"s{self._step_counter}."


def microbatch_slicer(batch: Any, index: int, count: int) -> Any:
    """Default microbatch_fn: slice dim 0 of every leaf into ``count``
    equal chunks and take chunk ``index``."""
    import jax

    def _slice(x):
        n = np.shape(x)[0]
        if n % count != 0:
            raise ValueError(
                f"batch dim {n} not divisible by microbatches={count}"
            )
        size = n // count
        return x[index * size : (index + 1) * size]

    return jax.tree.map(_slice, batch)

"""MPMD pipeline-stage runner — (interleaved) 1F1B across slice gangs.

Each pipeline rank is a SEPARATE program on its own gang worker (MPMD:
"Scaling Deep Learning Training with MPMD Pipeline Parallelism"), holding
one or more contiguous chunks of the model's layers. The driver-visible
contract is unchanged — workers run an ordinary train loop and
``report()`` per step — but inside the step this runner executes the
per-rank op stream from ``parallel.pipeline.schedule_interleaved_1f1b``,
handing activations (forward) and activation-cotangents (backward) to
neighbor ranks over the collective p2p plane.

Interleaved 1F1B (ISSUE 11): with ``virtual > 1`` chunks per rank, chunk
``c`` on rank ``r`` is virtual stage ``c * num_stages + r`` — the virtual
pipeline wraps the physical ring ``virtual`` times, shrinking the
fill/drain bubble from (S−1)/(M+S−1) to (S−1)/(v·M+S−1) at the cost of
``virtual − 1`` extra activation hand-offs per microbatch. The p2p links
are unchanged: every virtual edge vs→vs+1 is the same physical
next-neighbor hop.

Activation wire (ISSUE 11): with
``CollectiveConfig(quantize_activations="int8"|"fp8")`` the PR-7
block-scaled codec extends from gradient allreduce to the activation /
cotangent hand-offs, with per-edge persistent error-feedback residuals
(keyed by direction × microbatch × virtual stage, so step t's rounding
error corrects step t+1's message on the SAME edge). The loss broadcast
and any non-float payload always ride the exact wire, and the codec is
host-memory only (ring/hier backends) — the xla p2p path stays exact.

Inside a stage, dp/fsdp/tp still apply: the stage's params are sharded
over the worker's local GSPMD mesh with the same logical-dim rules the
non-pipelined path uses — pp composes with the other axes.

Memory follows the 1F1B bound on stashed inputs (scaled by ``virtual``)
and backward recomputes the chunk forward from the saved INPUT (full
per-chunk remat) instead of holding vjp residuals — the standard MPMD
trade: activations-in-flight stay O(microbatch), at one extra forward of
FLOPs per microbatch.

Stage-level StepStats: wall time spent blocked in ``recv`` is attributed
to the ``pp_bubble`` phase, so the flight recorder's per-step breakdown
separates schedule bubbles from real compute and the release gate can
assert bubble ≤ its bound — which is exactly how the interleaved
schedule's smaller bubble shows up as a measured number.

Checkpointing under pp > 1 is deliberately per-stage-local for now: the
committed-checkpoint reshard protocol covers (dp, fsdp, tp); resharding
across DIFFERENT pipeline factorizations requires merging stage trees
through models.transformer.merge_stages on rank 0 first (see
docs/sharding.md, "Pipeline stages and checkpoints").
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import numpy as np

from ray_tpu.dag.channels import DeviceChannel
from ray_tpu.train._internal import step_stats


class PipelineStageRunner:
    """Runs ONE rank's half of the (interleaved) 1F1B schedule.

    Parameters
    ----------
    stage_fn : (chunk_params, activations) -> activations, or a sequence
        of ``virtual`` such callables (one per local chunk; chunk ``c``
        is virtual stage ``c * num_stages + rank``). The FIRST virtual
        stage receives the microbatch's model inputs instead of
        activations.
    last_stage_fn : (chunk_params, activations, microbatch) -> scalar loss
        Used for the LAST virtual stage (last rank's last chunk); closes
        over targets.
    params : pytree, or a sequence of ``virtual`` pytrees
        This rank's chunk parameters (possibly GSPMD-sharded).
    optimizer : optax-like GradientTransformation.
    activation_like : (microbatch) -> jax.ShapeDtypeStruct
        Wire shape/dtype of one microbatch's activations — recv needs it
        to allocate the buffer (the p2p plane is untyped bytes).
    microbatch_fn : (batch, index, count) -> microbatch
        Slices microbatch ``index`` of ``count`` out of a global batch.
    """

    def __init__(
        self,
        *,
        ctx: Any,
        stage_fn: Callable | Sequence[Callable],
        last_stage_fn: Callable,
        params: Any,
        optimizer: Any,
        activation_like: Callable,
        microbatch_fn: Callable,
        param_shardings: Any = None,
        recv_timeout_s: float = 120.0,
    ):
        import jax

        from ray_tpu.parallel.pipeline import schedule_interleaved_1f1b
        from ray_tpu.util.collective import collective
        from ray_tpu.util.collective.quantization import ErrorFeedback

        pipe = ctx.pipeline
        if not pipe:
            raise ValueError(
                "PipelineStageRunner needs ScalingConfig.pipeline_stages > 1 "
                "(TrainContext.pipeline is unset)"
            )
        self.stage = int(pipe["stage"])
        self.num_stages = int(pipe["num_stages"])
        self.microbatches = int(pipe["microbatches"])
        self.virtual = int(pipe.get("virtual", 1))
        if ctx.world_size != self.num_stages:
            raise NotImplementedError(
                "stage gangs wider than one worker are not wired yet: "
                f"world_size={ctx.world_size} != "
                f"pipeline_stages={self.num_stages}"
            )
        self.group = collective.get_group(ctx.collective_group)
        self.optimizer = optimizer
        self.activation_like = activation_like
        self.microbatch_fn = microbatch_fn
        self.recv_timeout_s = float(recv_timeout_s)
        self.schedule = schedule_interleaved_1f1b(
            self.num_stages, self.microbatches, self.stage, self.virtual
        )

        # Per-chunk state. v == 1 callers keep passing a single tree /
        # callable; v > 1 callers pass one per chunk.
        stage_fns = (
            list(stage_fn)
            if isinstance(stage_fn, (list, tuple))
            else [stage_fn] * self.virtual
        )
        chunk_params = (
            list(params)
            if isinstance(params, (list, tuple))
            else [params]
        )
        if len(stage_fns) != self.virtual or len(chunk_params) != self.virtual:
            raise ValueError(
                f"need {self.virtual} stage_fns/param chunks "
                f"(virtual={self.virtual}), got {len(stage_fns)} fns / "
                f"{len(chunk_params)} param trees"
            )
        self._chunk_params = chunk_params
        self._opt_states = [optimizer.init(p) for p in chunk_params]

        self._fwd = [jax.jit(fn) for fn in stage_fns]

        def _make_bwd(fn):
            def _bwd(p, a, ct):
                # Recompute-forward backward: vjp INSIDE jit so residuals
                # never outlive the call (the memory bound holds on
                # stashed inputs, not activation stacks).
                _, vjp_fn = jax.vjp(fn, p, a)
                return vjp_fn(ct)

            return jax.jit(_bwd)

        self._bwd = [_make_bwd(fn) for fn in stage_fns]
        self._last_grad = jax.jit(
            jax.value_and_grad(last_stage_fn, argnums=(0, 1))
        )

        def _apply(p, o, g):
            updates, new_o = self.optimizer.update(g, o, p)
            new_p = jax.tree.map(
                lambda w, u: w + u.astype(w.dtype), p, updates
            )
            return new_p, new_o

        self._apply = jax.jit(_apply, donate_argnums=(0, 1))
        self._param_shardings = param_shardings
        # Step-tag namespace fencing across gang re-formations: each
        # launch attempt starts its counter in its own disjoint range, so
        # a frame a dying peer left in a mailbox can never pair with the
        # re-formed gang's traffic (same fix as the rtdag channel epoch,
        # expressed inside the existing ``s{N}.`` tag shape so the static
        # commgraph skeleton is unchanged).
        self._step_counter = int(pipe.get("attempt", 0)) * 1_000_000

        # Activation-wire codec (ISSUE 11): host-memory backends only —
        # the xla p2p plane moves device arrays and stays exact.
        cfg = self.group.config
        self._act_cfg = None
        if (
            getattr(cfg, "quantize_activations", None)
            and getattr(self.group, "backend_name", "") in ("ring", "hier")
        ):
            self._act_cfg = cfg.activation_wire_config()
        self._act_ef = ErrorFeedback()
        # Neighbor rings as rtdag device channels (ISSUE 15): the 1F1B
        # activation wire is the same channel family a compiled DAG edge
        # uses — tagged mode, with the codec/EF state owned per edge.
        # The gang-formation attempt is the rings' channel epoch: after a
        # gang death + re-form, a frame a dying peer left in flight can
        # never be mistaken for the new incarnation's traffic.
        attempt = int(pipe.get("attempt", 0))
        self._prev_ring = DeviceChannel(
            self.group, (self.stage - 1) % self.num_stages,
            site="pipeline", wire_cfg=self._act_cfg, ef=self._act_ef,
            epoch=attempt,
        )
        self._next_ring = DeviceChannel(
            self.group, (self.stage + 1) % self.num_stages,
            site="pipeline", wire_cfg=self._act_cfg, ef=self._act_ef,
            epoch=attempt,
        )

    # -- back-compat single-chunk views -----------------------------------
    @property
    def params(self) -> Any:
        """The single-chunk param tree (v == 1 callers), or the chunk
        list under interleaving."""
        return (
            self._chunk_params[0] if self.virtual == 1 else self._chunk_params
        )

    @params.setter
    def params(self, value: Any) -> None:
        if self.virtual == 1:
            self._chunk_params[0] = value
        else:
            self._chunk_params = list(value)

    @property
    def opt_state(self) -> Any:
        return (
            self._opt_states[0] if self.virtual == 1 else self._opt_states
        )

    # -- virtual-stage helpers -------------------------------------------
    def _virtual_stage(self, chunk: int) -> int:
        return chunk * self.num_stages + self.stage

    @property
    def num_virtual_stages(self) -> int:
        return self.num_stages * self.virtual

    # -- p2p plumbing -----------------------------------------------------
    def _recv(self, ring: DeviceChannel, tag: str, like):
        """Blocking neighbor pop; blocked wall time IS the pipeline
        bubble at this stage, so it lands in the pp_bubble phase. The
        channel decodes codec-compressed payloads before returning."""
        t0 = time.perf_counter()
        out = ring.pop(tag=tag, timeout=self.recv_timeout_s, like=like)
        step_stats.record_phase("pp_bubble", time.perf_counter() - t0)
        return out

    def _send(self, array, ring: DeviceChannel, tag: str, site=None) -> None:
        arr = np.asarray(array)  # rtlint: disable=host-sync-in-step - eager p2p hand-off IS the wire, not an accidental sync
        # With a wire codec configured, the channel block-scale-quantizes
        # float payloads; the per-edge EF residual (keyed by ``site`` =
        # direction × microbatch × virtual stage) telescopes this step's
        # rounding error into the next step's message on the SAME edge.
        ring.push(arr, tag=tag, ef_site=site)

    # -- one optimizer step ----------------------------------------------
    def train_step(self, batch: Any) -> float:
        """Run this rank's full op stream for one global batch and apply
        the chunk-local optimizer updates. Every rank returns the SAME
        mean microbatch loss (broadcast from the last rank)."""
        import jax

        grads_acc: list = [None] * self.virtual
        losses: list = []
        stash: dict[tuple, Any] = {}  # (micro, chunk) -> input / grads
        step_tag = self._next_tag()
        last_vs = self.num_virtual_stages - 1
        for op, m, c in self.schedule:
            vs = self._virtual_stage(c)
            micro = self.microbatch_fn(batch, m, self.microbatches)
            if op == "F":
                if vs == 0:
                    a_in = self._model_inputs(micro)
                else:
                    a_in = self._recv(
                        self._prev_ring,
                        f"{step_tag}f{m}v{vs}",
                        self.activation_like(micro),
                    )
                if vs == last_vs:
                    # Last virtual stage has no downstream cotangent to
                    # wait on: loss + grads in one fused value_and_grad —
                    # unsplittable, so the slice is attributed to bwd
                    # (backward dominates it).
                    with step_stats.step_annotation("bwd", phase="bwd"):
                        loss, (dp, da) = self._last_grad(
                            self._chunk_params[c], a_in, micro
                        )
                        jax.block_until_ready(dp)  # rtlint: disable=host-sync-in-step - attribution boundary; the grads feed the send/accumulate next anyway
                    losses.append(loss)
                    stash[(m, c)] = (dp, da)
                else:
                    stash[(m, c)] = a_in
                    with step_stats.step_annotation("fwd", phase="fwd"):
                        y = self._fwd[c](self._chunk_params[c], a_in)
                        jax.block_until_ready(y)  # rtlint: disable=host-sync-in-step - attribution boundary; _send materializes y on host next anyway
                    self._send(
                        y,
                        self._next_ring,
                        f"{step_tag}f{m}v{vs + 1}",
                        site=("f", m, vs),
                    )
            else:  # "B"
                if vs == last_vs:
                    dp, da = stash.pop((m, c))
                else:
                    ct = self._recv(
                        self._next_ring,
                        f"{step_tag}b{m}v{vs}",
                        self.activation_like(micro),
                    )
                    with step_stats.step_annotation("bwd", phase="bwd"):
                        dp, da = self._bwd[c](
                            self._chunk_params[c], stash.pop((m, c)), ct
                        )
                        jax.block_until_ready(dp)  # rtlint: disable=host-sync-in-step - attribution boundary; the grads feed the send/accumulate next anyway
                if vs > 0:
                    self._send(
                        da,
                        self._prev_ring,
                        f"{step_tag}b{m}v{vs - 1}",
                        site=("b", m, vs),
                    )
                grads_acc[c] = (
                    dp
                    if grads_acc[c] is None
                    else jax.tree.map(jax.numpy.add, grads_acc[c], dp)
                )
        with step_stats.step_annotation("opt", phase="opt"):
            for c in range(self.virtual):
                grads = jax.tree.map(
                    lambda g: g / self.microbatches, grads_acc[c]
                )
                self._chunk_params[c], self._opt_states[c] = self._apply(
                    self._chunk_params[c], self._opt_states[c], grads
                )
            jax.block_until_ready(self._chunk_params)  # rtlint: disable=host-sync-in-step - attribution boundary; next step's forwards consume the params anyway
        if self.stage == self.num_stages - 1:
            local = float(np.mean([np.asarray(l) for l in losses]))  # rtlint: disable=host-sync-in-step - loss leaves the device to ride the broadcast wire
        else:
            local = 0.0
        loss = self.group.broadcast(
            np.asarray([local], np.float32),  # rtlint: disable=host-sync-in-step - the broadcast wire carries host arrays by design
            src_rank=self.num_stages - 1,
        )
        return float(loss[0])  # rtlint: disable=host-sync-in-step - per-step loss is the report-path scalar every stage returns

    def _model_inputs(self, micro: Any) -> Any:
        """What the first stage feeds its forward: the microbatch's
        inputs. Dict batches use 'x'/'inputs'; arrays pass through."""
        if isinstance(micro, dict):
            for key in ("x", "inputs", "tokens"):
                if key in micro:
                    return micro[key]
            raise KeyError(
                "first-stage microbatch dict needs an 'x'/'inputs'/'tokens' "
                "entry"
            )
        return micro

    def _next_tag(self) -> str:
        # Per-step tag namespace: microbatch m of step k must never pair
        # with microbatch m of step k±1 on a fast/slow neighbor pair.
        # Per-INSTANCE counter: every stage calls train_step once per
        # global step, so instance counters advance in lockstep across
        # the gang (a shared/class counter would not).
        self._step_counter += 1
        return f"s{self._step_counter}."


def microbatch_slicer(batch: Any, index: int, count: int) -> Any:
    """Default microbatch_fn: slice dim 0 of every leaf into ``count``
    equal chunks and take chunk ``index``."""
    import jax

    def _slice(x):
        n = np.shape(x)[0]
        if n % count != 0:
            raise ValueError(
                f"batch dim {n} not divisible by microbatches={count}"
            )
        size = n // count
        return x[index * size : (index + 1) * size]

    return jax.tree.map(_slice, batch)

"""Checkpoint persistence + retention.

Role-equivalent of python/ray/train/_internal/storage.py :: StorageContext.
Persists worker-reported checkpoint directories into
`<storage_path>/<experiment>/<trial>/checkpoint_NNNNNN`, tracks
latest/best, and enforces CheckpointConfig retention (num_to_keep,
score-attribute ordering). Local filesystem only in this build; the fs
boundary is kept narrow (persist/list/delete) so a cloud fs can slot in.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig


class StorageContext:
    def __init__(
        self,
        storage_path: str,
        experiment_name: str,
        trial_name: str = "",
        checkpoint_config: CheckpointConfig | None = None,
    ):
        self.experiment_dir = os.path.join(
            os.path.expanduser(storage_path), experiment_name
        )
        self.trial_dir = (
            os.path.join(self.experiment_dir, trial_name)
            if trial_name
            else self.experiment_dir
        )
        os.makedirs(self.trial_dir, exist_ok=True)
        self.checkpoint_config = checkpoint_config or CheckpointConfig()
        self._index = 0
        self._kept: list[tuple[str, dict]] = []  # (path, metrics)
        self._load_state()

    # -- persistence of the tracker itself (for experiment resume) ------
    @property
    def _state_path(self) -> str:
        return os.path.join(self.trial_dir, ".storage_state.json")

    def _load_state(self) -> None:
        if os.path.exists(self._state_path):
            with open(self._state_path) as f:
                state = json.load(f)
            self._index = state["index"]
            self._kept = [
                (p, m) for p, m in state["kept"] if os.path.isdir(p)
            ]

    def _save_state(self) -> None:
        with open(self._state_path, "w") as f:
            json.dump({"index": self._index, "kept": self._kept}, f)

    # -- API -------------------------------------------------------------
    def persist(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        dest = os.path.join(self.trial_dir, f"checkpoint_{self._index:06d}")
        self._index += 1
        if os.path.abspath(checkpoint.path) != dest:
            if os.path.isdir(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint.path, dest)
            # The merged rank-0 temp dir has been persisted — reclaim /tmp.
            if checkpoint.path.startswith(tempfile.gettempdir()):
                shutil.rmtree(checkpoint.path, ignore_errors=True)
        clean_metrics = {
            k: v for k, v in metrics.items()
            if isinstance(v, (int, float, str, bool))
        }
        self._kept.append((dest, clean_metrics))
        self._enforce_retention()
        self._save_state()
        return Checkpoint(dest)

    def _enforce_retention(self) -> None:
        cfg = self.checkpoint_config
        if cfg.num_to_keep is None or len(self._kept) <= cfg.num_to_keep:
            return
        if cfg.checkpoint_score_attribute:
            # Drop the worst-scoring, but never the most recent (needed for
            # failure recovery).
            latest = self._kept[-1]
            candidates = self._kept[:-1]
            reverse = cfg.checkpoint_score_order == "max"
            candidates.sort(
                key=lambda pm: pm[1].get(
                    cfg.checkpoint_score_attribute,
                    float("-inf") if reverse else float("inf"),
                ),
                reverse=reverse,
            )
            keep = candidates[: cfg.num_to_keep - 1] + [latest]
            drop = [pm for pm in self._kept if pm not in keep]
            self._kept = [pm for pm in self._kept if pm in keep]
        else:
            drop = self._kept[: -cfg.num_to_keep]
            self._kept = self._kept[-cfg.num_to_keep :]
        for path, _ in drop:
            shutil.rmtree(path, ignore_errors=True)

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return Checkpoint(self._kept[-1][0]) if self._kept else None

    def best_checkpoint(self) -> Optional[Checkpoint]:
        cfg = self.checkpoint_config
        if not self._kept:
            return None
        if not cfg.checkpoint_score_attribute:
            return self.latest_checkpoint()
        reverse = cfg.checkpoint_score_order == "max"
        best = sorted(
            self._kept,
            key=lambda pm: pm[1].get(
                cfg.checkpoint_score_attribute,
                float("-inf") if reverse else float("inf"),
            ),
            reverse=reverse,
        )[0]
        return Checkpoint(best[0])

    def checkpoints(self) -> list[tuple[Checkpoint, dict]]:
        return [(Checkpoint(p), m) for p, m in self._kept]

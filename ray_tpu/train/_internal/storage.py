"""Checkpoint persistence + retention.

Role-equivalent of python/ray/train/_internal/storage.py :: StorageContext.
Persists worker-reported checkpoint directories into
`<storage_path>/<experiment>/<trial>/checkpoint_NNNNNN`, tracks
latest/best, and enforces CheckpointConfig retention (num_to_keep,
score-attribute ordering). Local filesystem only in this build; the fs
boundary is kept narrow (persist/list/delete) so a cloud fs can slot in.

Commit protocol (ISSUE 6): `persist` stages the incoming directory at
`checkpoint_NNNNNN.staging`, verifies the per-rank shard inventory
(`checkpoint.verify_sharded_checkpoint`), stamps a `COMMIT.json`, and only
then atomically renames to the final name — so `checkpoint_NNNNNN` either
exists complete-and-committed or not at all. `_load_state` reconciles with
disk on startup: committed dirs missing from the tracker state are adopted
(crash between rename and state save) and uncommitted / inventory-failing
leftovers are garbage-collected, so a torn save can never crash-loop the
trainer — `latest_checkpoint()` only ever returns committed dirs, falling
back to the previous committed one.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import tempfile
import time
from typing import Optional

from ray_tpu.train.checkpoint import (
    _COMMIT,
    Checkpoint,
    _atomic_write_json,
    is_committed,
    verify_sharded_checkpoint,
)
from ray_tpu.train.config import CheckpointConfig

logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^checkpoint_(\d{6})$")
_STAGING_SUFFIX = ".staging"

# Per-rank dataset-iterator state stamped into each committed checkpoint so
# a restart (at any world size) can resume ingest exactly (ISSUE 6 layer 2).
INGEST_FILE = "ingest.json"


class StorageContext:
    def __init__(
        self,
        storage_path: str,
        experiment_name: str,
        trial_name: str = "",
        checkpoint_config: CheckpointConfig | None = None,
    ):
        self.experiment_dir = os.path.join(
            os.path.expanduser(storage_path), experiment_name
        )
        self.trial_dir = (
            os.path.join(self.experiment_dir, trial_name)
            if trial_name
            else self.experiment_dir
        )
        os.makedirs(self.trial_dir, exist_ok=True)
        self.checkpoint_config = checkpoint_config or CheckpointConfig()
        self._index = 0
        self._kept: list[tuple[str, dict]] = []  # (path, metrics)
        self._load_state()

    # -- persistence of the tracker itself (for experiment resume) ------
    @property
    def _state_path(self) -> str:
        return os.path.join(self.trial_dir, ".storage_state.json")

    def _load_state(self) -> None:
        if os.path.exists(self._state_path):
            try:
                with open(self._state_path) as f:
                    state = json.load(f)
            except (OSError, ValueError) as exc:
                # Torn state file: fall back to disk reconciliation, which
                # rebuilds the tracker from committed dirs.
                logger.warning("unreadable %s (%s); rebuilding from disk",
                               self._state_path, exc)
                state = {"index": 0, "kept": []}
            self._index = state.get("index", 0)
            self._kept = [
                (p, m)
                for p, m in state.get("kept", [])
                if os.path.isdir(p) and is_committed(p)
            ]
        self._reconcile_disk()

    def _save_state(self) -> None:
        _atomic_write_json(
            self._state_path, {"index": self._index, "kept": self._kept}
        )

    def _reconcile_disk(self) -> None:
        """Adopt committed checkpoints the tracker missed and GC torn ones.

        Covers every crash window: mid-copy (a ``.staging`` leftover),
        mid-save (a checkpoint dir whose inventory fails), and between the
        commit rename and the tracker-state write (a committed dir missing
        from ``_kept``).
        """
        known = {p for p, _ in self._kept}
        try:
            names = sorted(os.listdir(self.trial_dir))
        except OSError:
            return
        changed = False
        for name in names:
            path = os.path.join(self.trial_dir, name)
            if name.endswith(_STAGING_SUFFIX) and os.path.isdir(path):
                logger.warning("GCing abandoned staging dir %s", path)
                shutil.rmtree(path, ignore_errors=True)
                continue
            m = _CKPT_RE.match(name)
            if not m or not os.path.isdir(path) or path in known:
                continue
            if not is_committed(path):
                logger.warning("GCing uncommitted checkpoint dir %s", path)
                shutil.rmtree(path, ignore_errors=True)
                continue
            ok, reason = verify_sharded_checkpoint(path)
            if not ok:
                logger.warning(
                    "GCing committed-but-unverifiable checkpoint %s: %s",
                    path, reason,
                )
                shutil.rmtree(path, ignore_errors=True)
                continue
            # Committed + verified but unknown to the tracker: adopt it with
            # the metrics recorded in its commit stamp.
            try:
                with open(os.path.join(path, _COMMIT)) as f:
                    commit = json.load(f)
            except (OSError, ValueError):
                commit = {}
            self._kept.append((path, commit.get("metrics", {})))
            changed = True
        if changed:
            self._kept.sort(key=lambda pm: pm[0])
            self._index = max(
                self._index,
                max(
                    int(_CKPT_RE.match(os.path.basename(p)).group(1)) + 1
                    for p, _ in self._kept
                ),
            )
            self._save_state()

    # -- API -------------------------------------------------------------
    def persist(
        self,
        checkpoint: Checkpoint,
        metrics: dict,
        ingest: dict | None = None,
    ) -> Checkpoint:
        """Two-phase commit of a reported checkpoint directory.

        Stage → verify inventory → stamp COMMIT.json → atomic rename.
        Raises IOError when the staged directory fails inventory
        verification (torn sharded save); the caller should skip this round
        and keep the previous committed checkpoint.
        """
        from ray_tpu.util import chaos

        dest = os.path.join(self.trial_dir, f"checkpoint_{self._index:06d}")
        clean_metrics = {
            k: v for k, v in metrics.items()
            if isinstance(v, (int, float, str, bool))
        }
        if os.path.abspath(checkpoint.path) != dest:
            staging = dest + _STAGING_SUFFIX
            if os.path.isdir(staging):
                shutil.rmtree(staging)
            if os.path.isdir(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint.path, staging)
            if ingest is not None:
                _atomic_write_json(os.path.join(staging, INGEST_FILE), ingest)
            ok, reason = verify_sharded_checkpoint(staging)
            if not ok:
                shutil.rmtree(staging, ignore_errors=True)
                raise IOError(
                    f"refusing to commit torn checkpoint {checkpoint.path}: "
                    f"{reason}"
                )
            # Kill window under test: shards staged + verified but no
            # COMMIT.json / final name yet — reconcile must GC this.
            chaos.failpoint("train.storage.pre_commit")
            _atomic_write_json(
                os.path.join(staging, _COMMIT),
                {
                    "index": self._index,
                    "ts": time.time(),
                    "metrics": clean_metrics,
                },
            )
            os.replace(staging, dest)
            # The merged rank-0 temp dir has been persisted — reclaim /tmp.
            if checkpoint.path.startswith(tempfile.gettempdir()):
                shutil.rmtree(checkpoint.path, ignore_errors=True)
        else:
            if ingest is not None:
                _atomic_write_json(os.path.join(dest, INGEST_FILE), ingest)
            if not is_committed(dest):
                _atomic_write_json(
                    os.path.join(dest, _COMMIT),
                    {
                        "index": self._index,
                        "ts": time.time(),
                        "metrics": clean_metrics,
                    },
                )
        self._index += 1
        self._kept.append((dest, clean_metrics))
        self._enforce_retention()
        self._save_state()
        return Checkpoint(dest)

    def _enforce_retention(self) -> None:
        cfg = self.checkpoint_config
        if cfg.num_to_keep is None or len(self._kept) <= cfg.num_to_keep:
            return
        if cfg.checkpoint_score_attribute:
            # Drop the worst-scoring, but never the most recent (needed for
            # failure recovery).
            latest = self._kept[-1]
            candidates = self._kept[:-1]
            reverse = cfg.checkpoint_score_order == "max"
            candidates.sort(
                key=lambda pm: pm[1].get(
                    cfg.checkpoint_score_attribute,
                    float("-inf") if reverse else float("inf"),
                ),
                reverse=reverse,
            )
            keep = candidates[: cfg.num_to_keep - 1] + [latest]
            drop = [pm for pm in self._kept if pm not in keep]
            self._kept = [pm for pm in self._kept if pm in keep]
        else:
            drop = self._kept[: -cfg.num_to_keep]
            self._kept = self._kept[-cfg.num_to_keep :]
        for path, _ in drop:
            shutil.rmtree(path, ignore_errors=True)

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        # Walk back from the newest: a kept entry whose dir lost its commit
        # stamp or inventory since tracking (external tampering, partial
        # delete) is skipped and GCed so recovery falls back to the
        # previous committed checkpoint instead of crash-looping.
        while self._kept:
            path, _ = self._kept[-1]
            if os.path.isdir(path) and is_committed(path):
                ok, reason = verify_sharded_checkpoint(path)
                if ok:
                    return Checkpoint(path)
                logger.warning(
                    "dropping unverifiable checkpoint %s: %s", path, reason
                )
            else:
                logger.warning("dropping uncommitted checkpoint %s", path)
            self._kept.pop()
            shutil.rmtree(path, ignore_errors=True)
            self._save_state()
        return None

    def latest_ingest(self) -> Optional[dict]:
        """The per-rank dataset-iterator state stamped into the newest
        committed checkpoint, or None when it carries none."""
        ckpt = self.latest_checkpoint()
        if ckpt is None:
            return None
        path = os.path.join(ckpt.path, INGEST_FILE)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def best_checkpoint(self) -> Optional[Checkpoint]:
        cfg = self.checkpoint_config
        if not self._kept:
            return None
        if not cfg.checkpoint_score_attribute:
            return self.latest_checkpoint()
        reverse = cfg.checkpoint_score_order == "max"
        best = sorted(
            self._kept,
            key=lambda pm: pm[1].get(
                cfg.checkpoint_score_attribute,
                float("-inf") if reverse else float("inf"),
            ),
            reverse=reverse,
        )[0]
        return Checkpoint(best[0])

    def checkpoints(self) -> list[tuple[Checkpoint, dict]]:
        return [(Checkpoint(p), m) for p, m in self._kept]

"""Per-worker train session.

Role-equivalent of python/ray/train/_internal/session.py :: _TrainSession —
the user's train loop runs on a background thread; `report(metrics,
checkpoint)` hands (metrics, checkpoint) to the trainer's polling loop and
blocks until consumed, which keeps every rank's loop in lockstep with the
driver the way the reference's session does.
"""

from __future__ import annotations

import queue
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train._internal import step_stats as step_stats_mod


@dataclass
class TrainContext:
    """What `ray_tpu.train.get_context()` returns inside a worker."""

    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    node_id: str = ""
    experiment_name: str = ""
    trial_dir: str = ""
    train_loop_config: dict = field(default_factory=dict)
    latest_checkpoint: Optional[Checkpoint] = None
    dataset_shards: dict = field(default_factory=dict)
    mesh: Any = None
    # SliceTopology when the trainer runs multi-slice (DCN x ICI axes);
    # worker loops pass it to jax_utils.build_mesh(topology=...).
    slice_topology: Any = None
    collective_group: str = ""
    # MPMD pipeline assignment (ISSUE 10), set when
    # ScalingConfig.pipeline_stages > 1: {"stage": s, "num_stages": S,
    # "microbatches": M}. The stage runner
    # (train._internal.stage_runner.PipelineStageRunner) reads it; None
    # means no pipeline — the plain GSPMD path.
    pipeline: Any = None

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_dir(self) -> str:
        return self.trial_dir


class _Session:
    def __init__(self, ctx: TrainContext, fn: Callable[[], Any]):
        self.ctx = ctx
        self._results: queue.Queue = queue.Queue(maxsize=1)
        self._consumed = threading.Event()
        self._consumed.set()
        self.error: Exception | None = None
        self.finished = threading.Event()
        # Workload flight recorder (ISSUE 8): one StepStats record per
        # report. Off → None, and the phase accumulator stays inactive.
        self._recorder = (
            step_stats_mod.StepRecorder(ctx)
            if step_stats_mod.enabled()
            else None
        )
        if self._recorder is not None:
            step_stats_mod.activate()
        self._thread = threading.Thread(
            target=self._run, args=(fn,), daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def _run(self, fn: Callable[[], Any]) -> None:
        try:
            fn()
        except Exception as exc:  # surfaced via next_result poll
            exc._traceback_str = traceback.format_exc()  # type: ignore[attr-defined]
            self.error = exc
        finally:
            self.finished.set()

    # -- called from the user thread ------------------------------------
    def report(
        self, metrics: dict, checkpoint: Checkpoint | None = None
    ) -> None:
        # Snapshot this rank's dataset-iterator positions alongside the
        # report: the driver stamps them into the committed checkpoint so a
        # restart (at any world size) resumes ingest exactly (ISSUE 6).
        ingest: dict[str, dict] = {}
        for name, shard in (self.ctx.dataset_shards or {}).items():
            if getattr(shard, "supports_state", False):
                try:
                    ingest[name] = shard.state_dict()
                except Exception:  # rtlint: disable=swallowed-exception - iterator snapshot is best-effort; resume falls back
                    pass
        # Cut the StepStats record BEFORE blocking on the driver: the
        # step interval must cover the user's work, not the driver's
        # poll latency (which would smear data/compute attribution).
        step_stats = (
            self._recorder.on_report(metrics)
            if self._recorder is not None
            else None
        )
        self._consumed.wait()
        self._consumed.clear()
        self._results.put(
            {
                "metrics": dict(metrics),
                "checkpoint": checkpoint,
                "ingest": ingest or None,
                "step_stats": step_stats,
            }
        )
        # Re-stamp the step clock AFTER the hand-off: the wait above is
        # the driver's rendezvous (every rank resumes on the same round
        # edge), and letting it bleed into the next record's wall makes
        # all ranks' walls equal the gang round period — hiding exactly
        # the per-rank dispersion the straggler detector keys on.
        if self._recorder is not None:
            self._recorder.mark_resume()

    # -- called from the actor (poll) -----------------------------------
    def next_result(self, timeout: float = 0.0) -> dict | None:
        """One reported result, or {'done': True}/{'error': ...} at the end."""
        try:
            item = self._results.get(timeout=timeout)
            self._consumed.set()
            return item
        except queue.Empty:
            pass
        if self.finished.is_set() and self._results.empty():
            if self.error is not None:
                return {
                    "error": self.error,
                    "traceback": getattr(self.error, "_traceback_str", ""),
                }
            return {"done": True}
        return None


_session: _Session | None = None


def init_session(ctx: TrainContext, fn: Callable[[], Any]) -> _Session:
    global _session
    _session = _Session(ctx, fn)
    return _session


def get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "ray_tpu.train.report()/get_context() called outside a train "
            "worker — they only work inside train_loop_per_worker."
        )
    return _session


def in_session() -> bool:
    return _session is not None


def shutdown_session() -> None:
    global _session
    step_stats_mod.deactivate()
    _session = None

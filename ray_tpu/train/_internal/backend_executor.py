"""BackendExecutor — drives a WorkerGang through a training run.

Role-equivalent of python/ray/train/_internal/backend_executor.py ::
BackendExecutor + worker_group.py :: WorkerGroup, collapsed onto the core
WorkerGang primitive (gangs already do placement-group scheduling, collective
rendezvous, and correlated-failure semantics — SURVEY §7.0.2).

Lockstep protocol: every rank's session must produce one result before the
executor hands the round to the trainer (matching the reference, where
`ray.train.report` is a barrier across workers).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
from typing import Any, Callable, Optional

from ray_tpu.train._internal.session import TrainContext, init_session
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.util.gang import WorkerGang


def _start_session_fn(
    gang_ctx,
    train_fn: Callable,
    train_loop_config: dict,
    experiment_name: str,
    trial_dir: str,
    latest_checkpoint: Optional[Checkpoint],
    dataset_shards_per_rank: list[dict],
    mesh_axes: dict,
    slice_topology=None,
    pipeline: dict | None = None,
) -> bool:
    if pipeline is not None:
        # MPMD stage assignment: gang rank r is stage r // gang_per_stage
        # (contiguous ranks form one stage's gang).
        num_stages = int(pipeline["num_stages"])
        per_stage = max(1, gang_ctx.world_size // num_stages)
        pipeline = {
            **pipeline,
            "stage": gang_ctx.rank // per_stage,
            "stage_rank": gang_ctx.rank % per_stage,
        }
    ctx = TrainContext(
        world_size=gang_ctx.world_size,
        world_rank=gang_ctx.rank,
        local_rank=0,
        node_id=gang_ctx.node_id,
        experiment_name=experiment_name,
        trial_dir=trial_dir,
        train_loop_config=dict(train_loop_config),
        latest_checkpoint=latest_checkpoint,
        dataset_shards=dataset_shards_per_rank[gang_ctx.rank],
        mesh=mesh_axes,
        slice_topology=slice_topology,
        collective_group=gang_ctx.group_name,
        pipeline=pipeline,
    )
    session = init_session(ctx, lambda: train_fn(dict(train_loop_config)))
    gang_ctx.state["session"] = session
    session.start()
    return True


def _poll_fn(gang_ctx, poll_timeout: float) -> dict | None:
    return gang_ctx.state["session"].next_result(timeout=poll_timeout)


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        scaling_config: ScalingConfig,
        *,
        backend: str = "ring",
        experiment_name: str,
        trial_dir: str,
    ):
        self.scaling_config = scaling_config
        self.backend = backend
        self.selected_backend = self._resolve_backend(backend)
        self.experiment_name = experiment_name
        self.trial_dir = trial_dir
        self.gang: WorkerGang | None = None

    def _resolve_backend(self, backend: str) -> str:
        """Topology-aware default (ISSUE 7): a ring-backend gang whose
        workers each own >1 local device upgrades to the hierarchical
        group — tier-1 in-jit psum over the local devices, tier-2 DCN
        ring of per-host partials — so only one partial per host rides
        the slow tier. Plain host-level allreduce on the hierarchical
        group delegates to its inner ring, so existing user code is
        unchanged. RAY_TPU_COLLECTIVE_AUTO_HIER=0 is the kill switch."""
        if backend != "ring":
            return backend
        if os.environ.get("RAY_TPU_COLLECTIVE_AUTO_HIER", "1") == "0":
            return backend
        if self._worker_local_devices() > 1:
            return "hier"
        return backend

    def _worker_local_devices(self) -> int:
        """Local device count a gang WORKER will see — from the worker
        env's host-platform flag (CPU twin) when present, else this
        process's jax runtime (real TPU hosts: driver and worker see the
        same per-host chip count)."""
        import re

        flags = dict(self.scaling_config.worker_env).get("XLA_FLAGS", "")
        m = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
        if m:
            return int(m.group(1))
        try:
            import jax

            return int(jax.local_device_count())
        except Exception:  # rtlint: disable=swallowed-exception - no jax on the driver: assume 1 local device
            return 1

    def start(
        self,
        train_fn: Callable,
        train_loop_config: dict,
        latest_checkpoint: Optional[Checkpoint],
        dataset_shards_per_rank: list[dict] | Callable[[int], list[dict]],
        attempt: int = 0,
    ) -> None:
        sc = self.scaling_config
        self.gang = self._form_gang()
        if callable(dataset_shards_per_rank):
            # Elastic path: shards depend on the world size actually formed.
            dataset_shards_per_rank = dataset_shards_per_rank(
                self.gang.num_workers
            )
        self.gang.run(
            _start_session_fn,
            train_fn=train_fn,
            train_loop_config=train_loop_config,
            experiment_name=self.experiment_name,
            trial_dir=self.trial_dir,
            latest_checkpoint=latest_checkpoint,
            dataset_shards_per_rank=dataset_shards_per_rank,
            mesh_axes=dict(sc.mesh_axes),
            slice_topology=sc.slice_topology,
            pipeline=(
                {
                    "num_stages": int(sc.pipeline_stages),
                    "microbatches": int(sc.microbatches),
                    "virtual": int(getattr(sc, "virtual_stages", 1)),
                    # Launch-attempt generation: the stage runner fences
                    # its p2p wire tags per attempt, so a re-formed gang
                    # never consumes a dead incarnation's frames.
                    "attempt": int(attempt),
                }
                if int(getattr(sc, "pipeline_stages", 1)) > 1
                else None
            ),
        )

    def _form_gang(self) -> WorkerGang:
        """Form the gang at the target size, stepping down to min_workers.

        Bounded elasticity (SURVEY §2.4 Train v2, §5.3): each size gets one
        formation attempt with a bounded placement timeout; a cluster that
        lost capacity re-forms at the largest world size it can still gang-
        schedule. Fixed-size configs keep the old behavior (one attempt,
        long timeout, hard failure).
        """
        from ray_tpu import exceptions

        sc = self.scaling_config
        # Multi-slice: the gang shares one jax.distributed runtime so the
        # training step is one XLA program over every slice's devices.
        coordinator = "auto" if sc.slice_topology is not None else None
        env_vars = dict(sc.worker_env) or None
        if not sc.elastic:
            return WorkerGang(
                sc.total_workers,
                resources_per_worker=sc.worker_resources(),
                backend=self.selected_backend,
                placement_strategy=sc.placement_strategy,
                coordinator=coordinator,
                env_vars=env_vars,
                collective_config=sc.collective_config,
            )
        last_exc: Exception | None = None
        for size in range(sc.total_workers, sc.min_workers - 1, -1):
            try:
                gang = WorkerGang(
                    size,
                    resources_per_worker=sc.worker_resources(),
                    backend=self.selected_backend,
                    placement_strategy=sc.placement_strategy,
                    ready_timeout=sc.elastic_formation_timeout_s,
                    coordinator=coordinator,
                    env_vars=env_vars,
                    collective_config=sc.collective_config,
                )
                if size < sc.total_workers:
                    print(
                        f"[train] elastic step-down: formed gang at "
                        f"world_size={size} (target {sc.total_workers})"
                    )
                return gang
            except (
                exceptions.PlacementGroupUnschedulableError,
                exceptions.GangDiedError,
            ) as exc:
                last_exc = exc
        raise TrainingFailedError(
            f"could not form a gang at any size in "
            f"[{sc.min_workers}, {sc.total_workers}]: {last_exc}"
        )

    def poll_round(self, timeout: float = 600.0) -> list[dict]:
        """Block until every rank produced one result (or finished/errored).

        Returns the per-rank result dicts. Raises GangDiedError if a member
        process dies (the trainer turns that into restart-from-checkpoint).
        """
        assert self.gang is not None
        import ray_tpu
        from ray_tpu import exceptions

        deadline = time.monotonic() + timeout
        results: dict[int, dict] = {}
        pending = set(range(self.gang.num_workers))
        while pending:
            if time.monotonic() > deadline:
                raise TrainingFailedError(
                    f"train workers stalled: only {len(results)}/"
                    f"{self.gang.num_workers} ranks reported within {timeout}s"
                )
            # Poll ONLY ranks still missing a result this round — polling a
            # rank that already reported would consume (and drop) its next
            # report, breaking the cross-rank lockstep.
            refs = {
                rank: self.gang.members[rank].run.remote(
                    _poll_fn, (), {"poll_timeout": 1.0}
                )
                for rank in sorted(pending)
            }
            for rank, ref in refs.items():
                # Per-rank get is bounded by BOTH the local liveness cap and
                # the caller's remaining round deadline — a 600s poll_round
                # must not block 120s per rank past its own budget.
                remaining = deadline - time.monotonic()
                per_get = max(1.0, min(120.0, remaining))
                try:
                    res = ray_tpu.get(ref, timeout=per_get)
                except exceptions.GetTimeoutError as exc:
                    missing = sorted(pending)
                    raise TrainingFailedError(
                        f"train workers stalled: ranks {missing} did not "
                        f"report within the {timeout}s round deadline"
                    ) from exc
                except (
                    exceptions.ActorDiedError,
                    exceptions.ActorUnavailableError,
                    exceptions.WorkerCrashedError,
                ) as exc:
                    raise exceptions.GangDiedError(
                        f"gang member rank={rank} died during training: {exc}"
                    ) from exc
                if res is not None:
                    results[rank] = res
                    pending.discard(rank)
        return [results[r] for r in range(self.gang.num_workers)]

    def merge_sharded_checkpoints(self, reported: list[Optional[Checkpoint]]) -> Optional[Checkpoint]:
        """Rank 0's checkpoint dir is canonical; other ranks' `shards/p*`
        subdirs and `DONE.p<rank>` commit markers (written by
        checkpoint.save_pytree(process_index=rank)) are merged in so a
        multi-host sharded save arrives whole.

        The merged manifest's `world_size` is rewritten to the number of
        commit markers actually present: a replicated save (only rank 0
        reports a checkpoint) verifies as a one-writer checkpoint, while a
        sharded save that lost a writer's marker fails inventory
        verification at persist time and the round is skipped — fail
        closed, never commit a partial save.
        """
        from ray_tpu.train import checkpoint as ckpt_mod

        base = reported[0]
        if base is None:
            return None
        for ckpt in reported[1:]:
            if ckpt is None or ckpt.path == base.path:
                continue
            src_shards = os.path.join(ckpt.path, "shards")
            if os.path.isdir(src_shards):
                for proc_dir in os.listdir(src_shards):
                    dst = os.path.join(base.path, "shards", proc_dir)
                    if not os.path.isdir(dst):
                        shutil.copytree(
                            os.path.join(src_shards, proc_dir), dst
                        )
            for name in os.listdir(ckpt.path):
                if name.startswith("DONE.p"):
                    dst = os.path.join(base.path, name)
                    if not os.path.exists(dst):
                        shutil.copy2(os.path.join(ckpt.path, name), dst)
            # Rank temp dir is merged — reclaim /tmp (multi-GB models would
            # otherwise leak a checkpoint per report round per rank).
            if ckpt.path.startswith(tempfile.gettempdir()):
                shutil.rmtree(ckpt.path, ignore_errors=True)
        manifest_path = os.path.join(base.path, "manifest.json")
        if os.path.exists(manifest_path):
            import json

            try:
                with open(manifest_path) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                manifest = None
            if manifest is not None:
                markers = ckpt_mod._done_markers(base.path)
                manifest["world_size"] = max(1, len(markers))
                ckpt_mod._atomic_write_json(manifest_path, manifest)
        return base

    def shutdown(self) -> None:
        if self.gang is not None:
            self.gang.shutdown()
            self.gang = None

"""StepStats recording — worker and driver halves of the flight recorder.

Worker half (runs inside each train worker process):
  * a per-process phase accumulator — the collective layer and the
    checkpoint writers call :func:`record_phase` with measured wall time;
    ``activate()``/``deactivate()`` gate it so a non-train worker pays a
    single bool check.
  * :class:`StepRecorder` — the session calls ``on_report()`` once per
    ``train.report()``; it cuts one StepStats record covering the
    interval since the previous report: wall time, data-wait (delta of
    the dataset iterators' fetch-wait clocks), collective + checkpoint
    time (drained from the accumulator), compute as the remainder, plus
    tokens/FLOPs when the user's metrics carry them (keys ``tokens`` and
    ``flops``, per rank per step).

Driver half:
  * :class:`FlightRecorder` — one per ``fit()``. Ingests every rank's
    records each poll round into the
    :class:`~ray_tpu._private.workload.StepStatsAggregator`, pushes
    batched samples to the controller workload store (ONE throttled RPC,
    never per-record), and owns the goodput wall-clock buckets
    (checkpoint / restart / stalled; productive is the remainder, so the
    buckets always sum to wall).
"""

from __future__ import annotations

import contextlib
import logging
import sys
import threading
import time
from typing import Any

from ray_tpu._private import profiler as profiler_mod

logger = logging.getLogger(__name__)

_PUSH_INTERVAL_S = 1.0
_MAX_PENDING = 4096  # per-series driver-side buffer bound


def enabled() -> bool:
    try:
        from ray_tpu._private.config import global_config

        return bool(global_config().workload_stats_enabled)
    except Exception:  # rtlint: disable=swallowed-exception - config unreachable outside a cluster: default on
        return True


# -- worker-side phase accumulator --------------------------------------
_phase_lock = threading.Lock()
_phase_acc: dict[str, float] = {}
_active = False


def activate() -> None:
    global _active
    with _phase_lock:
        _phase_acc.clear()
    _active = True


def deactivate() -> None:
    global _active
    _active = False
    with _phase_lock:
        _phase_acc.clear()


def record_phase(phase: str, seconds: float) -> None:
    """Attribute ``seconds`` of the current step to ``phase``. Hot-path
    safe: outside an active train session this is one bool check."""
    if not _active:
        return
    if seconds <= 0:
        return
    with _phase_lock:
        _phase_acc[phase] = _phase_acc.get(phase, 0.0) + float(seconds)
    # Profile capture (ISSUE 20): phase totals during the capture window
    # feed the hot-phase attribution. One module-bool check when idle.
    profiler_mod.note_phase(phase, seconds)


_annotation_cls: Any = None


def _trace_annotation_cls() -> Any:
    """``jax.profiler.TraceAnnotation`` when the process already imported
    jax (never force a jax init for telemetry), else None. Cached after
    the first successful probe."""
    global _annotation_cls
    if _annotation_cls is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return None
        try:
            _annotation_cls = jax.profiler.TraceAnnotation
        except Exception:  # rtlint: disable=swallowed-exception - ancient jax without profiler: annotations degrade to timers
            return None
    return _annotation_cls


@contextlib.contextmanager
def step_annotation(name: str, phase: str | None = None):
    """Named sub-step scope (ISSUE 20): times the block, opens a
    ``jax.profiler.TraceAnnotation`` so the device trace carries the same
    name, attributes the wall time to a StepStats ``phase`` (fwd/bwd/opt)
    when asked, and — only while a capture is live — buffers the slice
    for the merged Perfetto trace. Idle cost is one timer read pair plus
    a no-op TraceAnnotation."""
    cls = _trace_annotation_cls()
    ann = cls(name) if cls is not None else None
    wall0 = time.time()
    t0 = time.perf_counter()
    if ann is not None:
        ann.__enter__()
    try:
        yield
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        dt = time.perf_counter() - t0
        if phase is not None:
            record_phase(phase, dt)
        profiler_mod.note_annotation(name, wall0, dt)


def _drain_phases() -> dict[str, float]:
    with _phase_lock:
        out = dict(_phase_acc)
        _phase_acc.clear()
    return out


def _device_info() -> tuple[str, int]:
    """(device_kind, local device count) — probed from jax only when the
    worker already imported it (never force a jax init for telemetry)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return "", 1
    try:
        devices = jax.local_devices()
        return devices[0].device_kind, len(devices)
    except Exception:
        return "", 1


class StepRecorder:
    """Cuts one StepStats record per ``train.report()`` on a worker."""

    def __init__(self, ctx: Any):
        self.ctx = ctx
        self.step = -1
        self._last = time.perf_counter()
        self._last_wait = 0.0
        self._device_kind: str | None = None
        self._devices = 1
        # The capture plane learns this worker's identity here so a
        # controller-armed profile can align on the step stream.
        profiler_mod.get_plane().set_meta(
            rank=ctx.world_rank, node_id=ctx.node_id
        )

    def _data_wait_total(self) -> float:
        total = 0.0
        for shard in (self.ctx.dataset_shards or {}).values():
            wait = getattr(shard, "fetch_wait_s", None)
            if isinstance(wait, (int, float)):
                total += float(wait)
        return total

    def on_report(self, metrics: dict) -> dict:
        now = time.perf_counter()
        wall = max(0.0, now - self._last)
        self._last = now
        wait_total = self._data_wait_total()
        data_wait = min(wall, max(0.0, wait_total - self._last_wait))
        self._last_wait = wait_total
        phases = _drain_phases()
        collective = min(wall, phases.get("collective", 0.0))
        checkpoint = min(wall, phases.get("checkpoint", 0.0))
        # Pipeline-stage recv waits (stage_runner): schedule bubble, not
        # compute — subtracted from the remainder like the other phases.
        pp_bubble = min(wall, phases.get("pp_bubble", 0.0))
        # Overlapped gradient sync (ISSUE 11): collective keeps the TOTAL
        # op time (the work still happened, on background threads), but
        # only the fence-blocked slice stole wall clock from the step —
        # so when the overlap path ran, the compute remainder subtracts
        # the exposed time instead of the total.
        comm_exposed = min(wall, phases.get("comm_exposed", 0.0))
        comm_blocking = comm_exposed if "comm_exposed" in phases else collective
        compute = max(
            0.0, wall - data_wait - comm_blocking - checkpoint - pp_bubble
        )
        # Sub-step attribution (ISSUE 20): step_annotation() scopes split
        # the compute remainder into fwd/bwd/opt. The split is clamped so
        # fwd+bwd+opt never exceeds compute (annotation walls can overlap
        # phases already subtracted above); compute itself is UNCHANGED —
        # the split refines it, never redefines it.
        fwd = phases.get("fwd", 0.0)
        bwd = phases.get("bwd", 0.0)
        opt = phases.get("opt", 0.0)
        sub = fwd + bwd + opt
        if sub > compute > 0.0:
            scale = compute / sub
            fwd, bwd, opt = fwd * scale, bwd * scale, opt * scale
        elif sub > 0.0 and compute <= 0.0:
            fwd = bwd = opt = 0.0
            sub = 0.0
        if self._device_kind is None:
            self._device_kind, self._devices = _device_info()
        self.step += 1
        rec = {
            "step": self.step,
            "ts": time.time(),
            "rank": self.ctx.world_rank,
            "node_id": self.ctx.node_id,
            "wall_s": wall,
            "data_wait_s": data_wait,
            "compute_s": compute,
            "collective_s": collective,
            "checkpoint_s": checkpoint,
            "pp_bubble_s": pp_bubble,
            "comm_exposed_s": comm_exposed,
        }
        if sub > 0.0:
            rec["fwd_s"] = fwd
            rec["bwd_s"] = bwd
            rec["opt_s"] = opt
        # Step boundary for the capture plane: this report ends step
        # `self.step` — an armed capture starts/stops exactly here, so
        # every selected rank cuts on the same global step edge.
        profiler_mod.on_step_boundary(self.step)
        tokens = metrics.get("tokens")
        if isinstance(tokens, (int, float)) and not isinstance(tokens, bool):
            rec["tokens"] = float(tokens)
        flops = metrics.get("flops")
        if isinstance(flops, (int, float)) and not isinstance(flops, bool):
            rec["flops"] = float(flops)
        if self._device_kind:
            rec["device_kind"] = self._device_kind
            rec["devices"] = self._devices
        return rec

    def mark_resume(self) -> None:
        """Exclude the driver's report rendezvous from the next wall.

        ``train.report()`` blocks until the trainer's poll loop consumes
        the previous result, so every rank resumes on the same round
        edge — gated by the slowest rank. Without this re-stamp that
        block lands in the NEXT step's wall and every rank's wall
        converges to the gang round period, which blinds the MAD
        straggler scan (a dragged rank reads as a uniform gang).
        ``session.report`` calls this after the hand-off so walls
        measure the rank's own step, not the driver's backpressure."""
        self._last = time.perf_counter()


async def _swallow(coro) -> None:
    """Await a fire-and-forget push; a failed push is a delayed snapshot,
    not an error (and must not leave 'exception never retrieved' noise)."""
    try:
        await coro
    except Exception:
        logger.debug("workload_ingest push failed", exc_info=True)


# -- driver side ---------------------------------------------------------
class FlightRecorder:
    """Driver-side aggregator + goodput accountant + store uplink."""

    def __init__(self, experiment: str, enabled_: bool | None = None):
        from ray_tpu._private.workload import StepStatsAggregator

        self.experiment = experiment
        self.enabled = enabled() if enabled_ is None else enabled_
        self.agg = StepStatsAggregator()
        self._t0 = time.monotonic()
        self.buckets = {
            "checkpoint_s": 0.0,
            "restart_s": 0.0,
            "stalled_s": 0.0,
        }
        self._last_progress: float | None = None
        self._pending: dict[str, list[dict]] = {}
        self._last_push = 0.0
        self._summary: dict | None = None
        self._last_summary = 0.0
        self.stragglers: list[dict] = []
        # Auto-profiling (ISSUE 20): ranks flagged straggler on
        # consecutive summary cuts debounce-trigger a bounded capture.
        self._straggler_streak: dict[int, int] = {}
        self._last_auto_req = 0.0

    # -- goodput wall-clock buckets -------------------------------------
    def note_restart(self, seconds: float) -> None:
        self.buckets["restart_s"] += max(0.0, seconds)

    def note_checkpoint(self, seconds: float) -> None:
        self.buckets["checkpoint_s"] += max(0.0, seconds)

    def note_progress(self) -> None:
        self._last_progress = time.monotonic()

    def note_stalled_since_progress(self) -> None:
        """The failure path: everything since the last committed round is
        lost work + detection time — the 'stalled' bucket."""
        if self._last_progress is not None:
            self.buckets["stalled_s"] += max(
                0.0, time.monotonic() - self._last_progress
            )
            self._last_progress = None

    def goodput(self) -> dict:
        from ray_tpu._private.workload import goodput_buckets

        return goodput_buckets(
            time.monotonic() - self._t0, **self.buckets
        )

    # -- per-round ingest -----------------------------------------------
    def on_round(self, round_results: list) -> dict | None:
        """Ingest one poll round's per-rank StepStats. Returns the rolling
        gang summary (tokens/s, MFU, phase fractions) or None when the
        recorder is off or the round carried no records."""
        self.note_progress()
        if not self.enabled:
            return None
        max_ckpt = 0.0
        saw = False
        for result in round_results:
            rec = result.get("step_stats") if isinstance(result, dict) else None
            if not isinstance(rec, dict):
                continue
            if self.agg.add(rec):
                saw = True
                max_ckpt = max(max_ckpt, float(rec.get("checkpoint_s") or 0.0))
                rank = rec.get("rank", 0)
                self._queue(f"train/{self.experiment}/rank{rank}", rec)
        if not saw:
            return self._summary
        # Workers save sharded checkpoints inside the step; the slowest
        # rank's save time is wall clock the gang spent checkpointing.
        self.buckets["checkpoint_s"] += max_ckpt
        # The rolling summary + straggler scan walk the whole window
        # (O(window x ranks)); at ms-scale steps doing that every
        # lockstep round is measurable overhead, so it runs on the push
        # cadence and rounds in between reuse the cached (<=1s stale)
        # summary. Raw per-rank records are still queued every round.
        now = time.monotonic()
        if self._summary is None or now - self._last_summary >= _PUSH_INTERVAL_S:
            self._last_summary = now
            self._summary = self._cut_gang_sample()
            self._maybe_push()
        return self._summary

    def _cut_gang_sample(self) -> dict:
        """Compute the rolling gang summary + straggler scan and queue it
        as one ``train/<experiment>`` sample."""
        summary = self.agg.summary()
        self.stragglers = self.agg.straggler_report(k=self._mad_k())
        if self.stragglers:
            summary["stragglers"] = [s["rank"] for s in self.stragglers]
        self._maybe_auto_profile()
        self._queue(
            f"train/{self.experiment}",
            {"ts": time.time(), **summary},
        )
        return summary

    def _maybe_auto_profile(self) -> None:
        """Debounce straggler flags into ONE profile_capture request.

        A rank must stay flagged for RAY_TPU_PROFILE_AUTO_CONSECUTIVE
        summary cuts (MAD blips don't profile); the driver then
        fire-and-forgets one controller RPC. The controller is the
        authority on cooldown/concurrency — this side only rate-limits
        its own requests so a persistent straggler doesn't spam."""
        if not self.stragglers:
            self._straggler_streak.clear()
            return
        if not profiler_mod.knob_bool("AUTO", True):
            return
        flagged = {
            int(s["rank"]) for s in self.stragglers if "rank" in s
        }
        for rank in list(self._straggler_streak):
            if rank not in flagged:
                del self._straggler_streak[rank]
        need = profiler_mod.knob_int("AUTO_CONSECUTIVE", 2)
        ready = []
        for rank in sorted(flagged):
            streak = self._straggler_streak.get(rank, 0) + 1
            self._straggler_streak[rank] = streak
            if streak >= need:
                ready.append(rank)
        if not ready:
            return
        now = time.monotonic()
        cooldown = profiler_mod.knob_float("AUTO_COOLDOWN_S", 300.0)
        if self._last_auto_req and now - self._last_auto_req < cooldown:
            return
        self._last_auto_req = now
        for rank in ready:
            self._straggler_streak[rank] = 0
        try:
            from ray_tpu._private import worker as worker_mod

            ctx = worker_mod.get_global_context()
            call = ctx.controller.call(
                "profile_capture",
                {
                    "steps": profiler_mod.knob_int("AUTO_STEPS", 3),
                    "ranks": ready,
                    "reason": "straggler",
                },
                timeout=10.0,
            )
            ctx.io.spawn(_swallow(call))
        except Exception:
            logger.debug("auto-profile trigger failed", exc_info=True)

    @staticmethod
    def _mad_k() -> float:
        try:
            from ray_tpu._private.config import global_config

            return float(global_config().straggler_mad_k)
        except Exception:  # rtlint: disable=swallowed-exception - config unreachable: default MAD k
            return 3.0

    # -- controller uplink ----------------------------------------------
    def _queue(self, key: str, sample: dict) -> None:
        pending = self._pending.setdefault(key, [])
        pending.append(sample)
        if len(pending) > _MAX_PENDING:
            del pending[: len(pending) - _MAX_PENDING]

    def _maybe_push(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < _PUSH_INTERVAL_S:
            return
        if not self._pending:
            return
        series = [
            {"key": key, "samples": samples}
            for key, samples in self._pending.items()
        ]
        self._pending = {}
        self._last_push = now
        try:
            from ray_tpu._private import worker as worker_mod

            ctx = worker_mod.get_global_context()
            call = ctx.controller.call(
                "workload_ingest", {"series": series}, timeout=10.0
            )
            if force:
                # finalize(): the goodput sample must land before fit()
                # returns, so the last push is synchronous.
                ctx.io.run(call)
            else:
                # Steady state: fire-and-forget on the io loop — the
                # driver's poll round must not block on the controller
                # round trip (a lost push only delays the next snapshot).
                ctx.io.spawn(_swallow(call))
        except Exception:
            logger.debug("workload_ingest push failed", exc_info=True)

    def finalize(self) -> dict:
        """End of fit(): compute final goodput, push it + any pending
        samples, and return the goodput buckets for ``Result.goodput``."""
        g = self.goodput()
        if self.enabled:
            if self.agg.records_ingested:
                # One fresh gang sample: the throttled cadence may have
                # left the last <1s of steps out of the stored series.
                self._summary = self._cut_gang_sample()
            self._queue(
                f"train/{self.experiment}/goodput",
                {"ts": time.time(), **g},
            )
            self._maybe_push(force=True)
        return g

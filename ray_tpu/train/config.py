"""AIR-style config dataclasses shared by train/tune.

Role-equivalents of the reference's python/ray/air/config.py ::
ScalingConfig / RunConfig / FailureConfig / CheckpointConfig, with TPU-first
vocabulary: workers are per-HOST gang members (one jax process per TPU host),
`topology` names a pod-slice shape, and `mesh_axes` declares the named
parallelism axes the trainer builds its jax.sharding.Mesh with.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Mapping


@dataclass
class ScalingConfig:
    """How many gang workers, with what resources, over what mesh.

    num_workers        — gang size (one worker per TPU host of the slice).
    use_tpu            — pin each worker to TPU resources.
    chips_per_worker   — TPU chips each worker's jax process owns.
    topology           — optional slice topology label (e.g. "v4-32"); the
                         scheduler treats it as a pod-slice placement-group
                         request (STRICT_PACK on the ICI domain).
    mesh_axes          — named axis sizes for the global device mesh, e.g.
                         {"dp": 4, "tp": 2}. Sizes must multiply to the
                         global chip count; {} means pure DP over all chips.
    resources_per_worker — extra scheduler resources per worker.
    placement_strategy — bundle placement: SPREAD (default, one worker per
                         node) / STRICT_SPREAD / PACK / STRICT_PACK.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    topology: str | None = None
    mesh_axes: Mapping[str, int] = field(default_factory=dict)
    # Multi-slice training (SURVEY §2.9 multi-slice row): a
    # parallel.topology.SliceTopology composing cross-slice DCN axes
    # with in-slice ICI axes. Workers read it from the train context
    # and pass it to jax_utils.build_mesh(topology=...). Setting it
    # makes the gang share ONE jax.distributed runtime (each worker
    # process = one slice's host set).
    slice_topology: Any = None
    # Extra env vars for every gang worker (e.g. the CPU twin's
    # XLA_FLAGS=--xla_force_host_platform_device_count=<n> so each
    # worker process models one slice with n devices).
    worker_env: Mapping[str, str] = field(default_factory=dict)
    resources_per_worker: Mapping[str, float] = field(default_factory=dict)
    placement_strategy: str = "SPREAD"
    # Bounded elasticity (reference: Train v2 min/max workers, SURVEY
    # §2.4): None ⇒ fixed world size. With min_workers set, a gang that
    # cannot re-form at num_workers after a failure restarts at the
    # largest feasible size ≥ min_workers — recovery is
    # checkpoint → re-mesh → restore, never in-place (XLA meshes are
    # static, SURVEY §5.3).
    min_workers: int | None = None
    # How long one formation attempt at a given size may wait before the
    # executor steps down to the next smaller world size.
    elastic_formation_timeout_s: float = 30.0
    # Grow-back probe (ISSUE 6): when elastic and running below
    # num_workers, the driver checks cluster capacity at most every this
    # many seconds and, when the missing bundles fit, resizes the gang
    # back up at the next checkpoint boundary. <= 0 disables growing.
    elastic_grow_probe_period_s: float = 5.0
    # Preemptive drain: when the resource-telemetry `oom_risk` channel
    # flags a node hosting a gang worker, checkpoint and re-form the gang
    # (replacing the worker if capacity exists elsewhere) before the
    # memory-monitor kill fires. Off by default: it requires telemetry.
    drain_on_oom_risk: bool = False
    # Wire-path knobs for the gang's collective group (ISSUE 7): a
    # ray_tpu.util.collective.CollectiveConfig, e.g.
    # CollectiveConfig(quantize="int8") to block-quantize DCN gradient
    # sync with error feedback. None ⇒ exact wire.
    collective_config: Any = None
    # MPMD pipeline parallelism across slices (ISSUE 10): with
    # pipeline_stages > 1 the gang's workers become pipeline STAGE gangs
    # (worker rank i runs stage i; num_workers must be a multiple of
    # pipeline_stages), each batch is cut into `microbatches` and
    # scheduled 1F1B, with activations handed stage→stage over the
    # collective p2p plane (always exact wire). dp/fsdp/tp still apply
    # INSIDE each stage via mesh_axes — pp composes with, not replaces,
    # the GSPMD axes.
    pipeline_stages: int = 1
    microbatches: int = 1
    # Interleaved 1F1B (ISSUE 11): each stage rank hosts this many model
    # CHUNKS (virtual pipeline stages), shrinking the fill/drain bubble
    # from (S-1)/(M+S-1) to (S-1)/(v*M+S-1). Requires microbatches
    # divisible by pipeline_stages when > 1; the model must partition
    # into pipeline_stages * virtual_stages chunks.
    virtual_stages: int = 1

    def worker_resources(self) -> dict[str, float]:
        resources = {"CPU": 1.0, **dict(self.resources_per_worker)}
        if self.use_tpu and "TPU" not in resources:
            resources["TPU"] = float(self.chips_per_worker or 1)
        return resources

    @property
    def total_workers(self) -> int:
        return int(self.num_workers)

    @property
    def elastic(self) -> bool:
        return (
            self.min_workers is not None
            and self.min_workers < self.num_workers
        )

    def factorization(self) -> dict[str, int]:
        """The (dp, fsdp, tp, pp) this config asks for. In-worker axes
        come from mesh_axes; pp from pipeline_stages; dp additionally
        multiplies in the cross-worker data-parallel replicas (workers
        not consumed as pipeline stages are data-parallel)."""
        axes = dict(self.mesh_axes)
        pp = max(1, int(self.pipeline_stages))
        dp_workers = max(1, self.num_workers // pp)
        return {
            "dp": int(axes.get("dp", 1)) * dp_workers,
            "fsdp": int(axes.get("fsdp", 1)),
            "tp": int(axes.get("tp", 1)),
            "pp": pp,
        }

    def __post_init__(self) -> None:
        if self.min_workers is not None and not (
            1 <= self.min_workers <= self.num_workers
        ):
            raise ValueError(
                "min_workers must satisfy 1 <= min_workers <= num_workers"
            )
        if self.pipeline_stages < 1 or self.microbatches < 1:
            raise ValueError(
                "pipeline_stages and microbatches must be >= 1"
            )
        if self.virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        if (
            self.virtual_stages > 1
            and self.microbatches % self.pipeline_stages != 0
        ):
            raise ValueError(
                f"interleaved 1F1B (virtual_stages={self.virtual_stages}) "
                f"needs microbatches divisible by pipeline_stages, got "
                f"microbatches={self.microbatches} "
                f"pipeline_stages={self.pipeline_stages}"
            )
        if (
            self.pipeline_stages > 1
            and self.num_workers % self.pipeline_stages != 0
        ):
            raise ValueError(
                f"num_workers={self.num_workers} must be a multiple of "
                f"pipeline_stages={self.pipeline_stages} (each stage is "
                f"a gang of num_workers/pipeline_stages workers)"
            )


@dataclass
class FailureConfig:
    """max_failures: gang restarts from the latest checkpoint before the run
    is declared failed. 0 = fail fast; -1 = retry forever.

    fail_fast: raise immediately on the first worker error (skips retries)."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    """num_to_keep: retain only the last/best K persisted checkpoints.
    checkpoint_score_attribute/order: 'best' selection for result + retention.
    checkpoint_frequency: used by trainers that drive their own loop."""

    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0

    def __post_init__(self) -> None:
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclass
class RunConfig:
    """Where results/checkpoints land and how failures are handled."""

    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    callbacks: list[Any] = field(default_factory=list)
    stop: Mapping[str, float] | None = None

    def resolved_storage_path(self) -> str:
        return os.path.expanduser(
            self.storage_path or "~/ray_tpu_results"
        )

"""ray_tpu.train — distributed training (Ray Train equivalent, TPU-first).

Public surface mirrors ray.train + ray.train.torch (SURVEY §2.4), with
JaxTrainer in TorchTrainer's role:

    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        ctx = train.get_context()
        ...
        train.report({"loss": l}, checkpoint=ckpt)

    JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=8)).fit()
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.train.checkpoint import (
    Checkpoint,
    load_pytree,
    load_pytree_checkpoint,
    save_pytree,
    save_pytree_checkpoint,
    verify_sharded_checkpoint,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.jax_trainer import DataParallelTrainer, JaxTrainer, Result
from ray_tpu.train._internal import session as _session_mod
from ray_tpu.train._internal.session import TrainContext


def report(metrics: dict, *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from a train worker.
    Blocks until the trainer consumed the previous round — a lockstep
    barrier across ranks, like the reference's ray.train.report."""
    _session_mod.get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return _session_mod.get_session().ctx


def get_checkpoint() -> Optional[Checkpoint]:
    return _session_mod.get_session().ctx.latest_checkpoint


def get_dataset_shard(name: str = "train"):
    return _session_mod.get_session().ctx.dataset_shards.get(name)


__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
    "save_pytree",
    "load_pytree",
    "save_pytree_checkpoint",
    "load_pytree_checkpoint",
    "verify_sharded_checkpoint",
]

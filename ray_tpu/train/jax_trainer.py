"""JaxTrainer / DataParallelTrainer — distributed training on gangs.

Role-equivalent of python/ray/train/data_parallel_trainer.py ::
DataParallelTrainer + torch/torch_trainer.py :: TorchTrainer, re-designed
TPU-first (SURVEY §3.3, §7.1 P6):

  * workers are gang members — one jax process per TPU host, gang-scheduled
    via a placement group; on real slices they share one jax.distributed
    runtime so the training step is ONE jitted XLA program whose psum /
    all_gather collectives ride ICI.
  * the "ring" backend is the CPU test twin (SURVEY §4.4.4): per-process
    jax + eager host-memory allreduce through ray_tpu.util.collective.
  * failure recovery is slice-granular (SURVEY §5.3): any member death ⇒
    GangDiedError ⇒ restart the whole gang from the latest persisted
    checkpoint, up to FailureConfig.max_failures.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu.train._internal.backend_executor import (
    BackendExecutor,
    TrainingFailedError,
)
from ray_tpu.train._internal.storage import StorageContext
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig


@dataclass
class Result:
    """What fit() returns — mirrors ray.train.Result."""

    metrics: dict = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[Exception] = None
    metrics_history: list = field(default_factory=list)

    @property
    def best_checkpoints(self) -> list:
        return [self.checkpoint] if self.checkpoint else []


def _split_datasets(datasets: dict, num_workers: int) -> list[dict]:
    """Per-rank dataset shards. A ray_tpu.data.Dataset splits via
    streaming_split (locality-aware iterators); plain sequences shard by
    striding; anything else is replicated."""
    shards: list[dict] = [dict() for _ in range(num_workers)]
    for name, ds in (datasets or {}).items():
        if hasattr(ds, "streaming_split"):
            for rank, it in enumerate(ds.streaming_split(num_workers)):
                shards[rank][name] = it
        elif isinstance(ds, (list, tuple)):
            for rank in range(num_workers):
                shards[rank][name] = ds[rank::num_workers]
        else:
            for rank in range(num_workers):
                shards[rank][name] = ds
    return shards


class DataParallelTrainer:
    """N workers × train_loop_per_worker(config), lockstep report rounds."""

    _default_backend = "ring"

    def __init__(
        self,
        train_loop_per_worker: Callable[[dict], Any],
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint: Checkpoint | None = None,
        backend: str | None = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.backend = backend or self._default_backend

    # -- hooks for Tune integration (tune wraps fit() in a trial actor) --
    def _experiment_name(self) -> str:
        return self.run_config.name or type(self).__name__.lower()

    def fit(self) -> Result:
        from ray_tpu._private import usage

        usage.record_feature("train")
        run_cfg = self.run_config
        storage = StorageContext(
            run_cfg.resolved_storage_path(),
            self._experiment_name(),
            checkpoint_config=run_cfg.checkpoint_config,
        )
        latest_ckpt = self.resume_from_checkpoint or storage.latest_checkpoint()
        failures = 0
        last_metrics: dict = {}
        history: list[dict] = []
        error: Exception | None = None

        while True:
            executor = BackendExecutor(
                self.scaling_config,
                backend=self.backend,
                experiment_name=self._experiment_name(),
                trial_dir=storage.trial_dir,
            )
            try:
                executor.start(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    latest_ckpt,
                    # Split AFTER gang formation: an elastic restart may
                    # come up at a smaller world size.
                    lambda world_size: _split_datasets(
                        self.datasets, world_size
                    ),
                )
                done, last_metrics, error = self._drive(
                    executor, storage, history, last_metrics
                )
                if done:
                    break
            except Exception as exc:
                from ray_tpu import exceptions as core_exc

                recoverable = isinstance(
                    exc,
                    (
                        core_exc.GangDiedError,
                        core_exc.ActorDiedError,
                        core_exc.WorkerCrashedError,
                        TrainingFailedError,
                    ),
                )
                if not recoverable:
                    raise
                error = exc
            finally:
                executor.shutdown()

            if error is not None:
                max_failures = run_cfg.failure_config.max_failures
                if run_cfg.failure_config.fail_fast or (
                    0 <= max_failures <= failures
                ):
                    break
                failures += 1
                latest_ckpt = storage.latest_checkpoint()
                error = None
                time.sleep(0.1)
                continue
            break

        return Result(
            metrics=last_metrics,
            checkpoint=storage.best_checkpoint(),
            path=storage.trial_dir,
            error=error,
            metrics_history=history,
        )

    def _drive(
        self,
        executor: BackendExecutor,
        storage: StorageContext,
        history: list,
        last_metrics: dict,
    ) -> tuple[bool, dict, Exception | None]:
        """Poll rounds until every rank is done, an error surfaces, or a
        stop criterion is met. Returns (done, last_metrics, error)."""
        stop = self.run_config.stop or {}
        while True:
            round_results = executor.poll_round()
            errors = [r for r in round_results if "error" in r]
            if errors:
                err = errors[0]["error"]
                err.worker_traceback = errors[0].get("traceback", "")  # type: ignore
                return True, last_metrics, err
            if all(r.get("done") for r in round_results):
                return True, last_metrics, None
            reports = [r for r in round_results if "metrics" in r]
            if not reports:
                continue
            metrics = dict(reports[0]["metrics"])
            ckpt = executor.merge_sharded_checkpoints(
                [r.get("checkpoint") for r in round_results]
            )
            if ckpt is not None:
                persisted = storage.persist(ckpt, metrics)
                metrics["checkpoint_path"] = persisted.path
            last_metrics = metrics
            history.append(metrics)
            for cb in self.run_config.callbacks:
                handler = getattr(cb, "on_result", None)
                if handler:
                    handler(metrics)
            if any(
                key in metrics and metrics[key] >= bound
                for key, bound in stop.items()
            ):
                return True, last_metrics, None


class JaxTrainer(DataParallelTrainer):
    """The flagship trainer. Same driver loop as DataParallelTrainer; the
    jax-specific machinery (mesh construction, param sharding, in-jit
    collectives, sharded checkpoints) lives in ray_tpu.train.jax_utils and
    runs inside train_loop_per_worker.

    backend="xla" (default on real slices) assumes gang members joined one
    jax.distributed runtime — collectives happen inside jit on ICI.
    backend="ring" (tests / CPU) gives eager host-memory collectives.

    ``topology=`` (a parallel.topology.SliceTopology) declares a
    multi-slice layout — cross-slice DCN axes composed with in-slice ICI
    axes; it reaches the workers via the train context
    (get_context().slice_topology → jax_utils.build_mesh(topology=...)).
    Implies the xla backend: the gang shares one jax.distributed runtime
    whose processes span the slices.
    """

    _default_backend = "ring"

    def __init__(self, *args, topology=None, **kwargs):
        super().__init__(*args, **kwargs)
        if topology is not None:
            self.scaling_config.slice_topology = topology
        if (
            self.scaling_config.use_tpu
            or self.scaling_config.slice_topology is not None
        ) and kwargs.get("backend") is None:
            self.backend = "xla"

"""JaxTrainer / DataParallelTrainer — distributed training on gangs.

Role-equivalent of python/ray/train/data_parallel_trainer.py ::
DataParallelTrainer + torch/torch_trainer.py :: TorchTrainer, re-designed
TPU-first (SURVEY §3.3, §7.1 P6):

  * workers are gang members — one jax process per TPU host, gang-scheduled
    via a placement group; on real slices they share one jax.distributed
    runtime so the training step is ONE jitted XLA program whose psum /
    all_gather collectives ride ICI.
  * the "ring" backend is the CPU test twin (SURVEY §4.4.4): per-process
    jax + eager host-memory allreduce through ray_tpu.util.collective.
  * failure recovery is slice-granular (SURVEY §5.3): any member death ⇒
    GangDiedError ⇒ restart the whole gang from the latest persisted
    checkpoint, up to FailureConfig.max_failures.

Elasticity (ISSUE 6): with ``min_workers`` set the trainer *resizes
instead of restarting*. A gang death re-forms at the surviving size with
full-jitter backoff; a periodic capacity probe grows the gang back toward
``num_workers`` at the next checkpoint boundary; and (opt-in) an
``oom_risk`` telemetry event on a gang node triggers a preemptive
checkpoint-and-replace before the memory-monitor kill fires. Every
transition goes checkpoint → re-form → restore — XLA meshes are static —
and dataset ingest resumes from the per-rank iterator states stamped into
the committed checkpoint, re-split across the new world size.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu.train._internal.backend_executor import (
    BackendExecutor,
    TrainingFailedError,
)
from ray_tpu.train._internal.storage import StorageContext
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.util.backoff import Backoff

logger = logging.getLogger(__name__)


@dataclass
class Result:
    """What fit() returns — mirrors ray.train.Result."""

    metrics: dict = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[Exception] = None
    metrics_history: list = field(default_factory=list)
    # Every world-size transition the run made: dicts of
    # {"reason": "gang_died"|"grow"|"oom_risk_drain", "from": k, "to": j}.
    resizes: list = field(default_factory=list)
    # Goodput accounting (ISSUE 8): the run's wall clock classified into
    # productive / checkpoint / restart / stalled buckets (they sum to
    # wall_s by construction) plus goodput_fraction.
    goodput: dict = field(default_factory=dict)

    @property
    def best_checkpoints(self) -> list:
        return [self.checkpoint] if self.checkpoint else []


def _split_datasets(
    datasets: dict, num_workers: int, ingest: dict | None = None
) -> list[dict]:
    """Per-rank dataset shards. A ray_tpu.data.Dataset splits via
    streaming_split (locality-aware iterators); plain sequences shard by
    striding; anything else is replicated.

    ``ingest`` is the per-rank iterator state stamped into the committed
    checkpoint being resumed ({"world_size": W, "datasets": {name:
    [state, ...]}}); Datasets then resume mid-epoch with the remaining
    sample space re-split across ``num_workers`` (which may differ from
    W). Striding of plain sequences is positionless and replays the
    epoch from the start — only Datasets get resume-exact semantics.
    """
    shards: list[dict] = [dict() for _ in range(num_workers)]
    per_ds_states = (ingest or {}).get("datasets", {})
    for name, ds in (datasets or {}).items():
        if hasattr(ds, "streaming_split"):
            resume_from = None
            if name in per_ds_states:
                resume_from = {
                    "world_size": (ingest or {}).get("world_size", 0),
                    "per_rank": per_ds_states[name],
                }
            for rank, it in enumerate(
                ds.streaming_split(num_workers, resume_from=resume_from)
            ):
                shards[rank][name] = it
        elif isinstance(ds, (list, tuple)):
            for rank in range(num_workers):
                shards[rank][name] = ds[rank::num_workers]
        else:
            for rank in range(num_workers):
                shards[rank][name] = ds
    return shards


def _session_events_dir_known() -> str | None:
    """The cluster session dir, when discoverable from this process."""
    sd = os.environ.get("RAYTPU_SESSION_DIR")
    if sd:
        return sd
    try:
        import ray_tpu

        return ray_tpu.runtime_info().get("session_dir")
    except Exception:  # rtlint: disable=swallowed-exception - no cluster context: no session dir
        return None


class DataParallelTrainer:
    """N workers × train_loop_per_worker(config), lockstep report rounds."""

    _default_backend = "ring"

    def __init__(
        self,
        train_loop_per_worker: Callable[[dict], Any],
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint: Checkpoint | None = None,
        backend: str | None = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.backend = backend or self._default_backend

    # -- hooks for Tune integration (tune wraps fit() in a trial actor) --
    def _experiment_name(self) -> str:
        return self.run_config.name or type(self).__name__.lower()

    def fit(self) -> Result:
        from ray_tpu._private import usage

        usage.record_feature("train")
        run_cfg = self.run_config
        storage = StorageContext(
            run_cfg.resolved_storage_path(),
            self._experiment_name(),
            checkpoint_config=run_cfg.checkpoint_config,
        )
        latest_ckpt = self.resume_from_checkpoint or storage.latest_checkpoint()
        failures = 0
        last_metrics: dict = {}
        history: list[dict] = []
        resizes: list[dict] = []
        error: Exception | None = None
        # Full-jitter restart backoff (shared Backoff helper): a node crash
        # that killed the gang often killed neighbours too — every trainer
        # re-forming on an identical schedule stampedes the controller.
        backoff = Backoff(initial_backoff_s=0.1, max_backoff_s=5.0)
        # oom_risk events are a monotone log; remember how many we have
        # already acted on so one event triggers one drain.
        oom_seen = 0
        # Workload flight recorder (ISSUE 8): per-round StepStats ingest +
        # goodput wall-clock buckets for this run.
        from ray_tpu.train._internal.step_stats import FlightRecorder

        recorder = FlightRecorder(self._experiment_name())

        while True:
            executor = BackendExecutor(
                self.scaling_config,
                backend=self.backend,
                experiment_name=self._experiment_name(),
                trial_dir=storage.trial_dir,
            )
            if executor.selected_backend != self.backend:
                logger.info(
                    "collective backend %r auto-upgraded to %r "
                    "(>1 local device per worker; set "
                    "RAY_TPU_COLLECTIVE_AUTO_HIER=0 to keep the flat ring)",
                    self.backend, executor.selected_backend,
                )
            resize: dict | None = None
            try:
                ingest = storage.latest_ingest() if latest_ckpt else None
                form_t0 = time.monotonic()
                try:
                    executor.start(
                        self.train_loop_per_worker,
                        self.train_loop_config,
                        latest_ckpt,
                        # Split AFTER gang formation: an elastic restart may
                        # come up at a smaller world size, and a resume re-splits
                        # the remaining sample space at whatever size formed.
                        lambda world_size: _split_datasets(
                            self.datasets, world_size, ingest=ingest
                        ),
                        # Launch-attempt generation — fences the pipeline
                        # p2p wire's tag namespace per gang incarnation.
                        attempt=failures,
                    )
                finally:
                    # Gang (re)formation is restart-resharding time whether
                    # it succeeded or died mid-form.
                    recorder.note_restart(time.monotonic() - form_t0)
                recorder.note_progress()
                backoff.reset()
                done, last_metrics, error, resize, oom_seen = self._drive(
                    executor, storage, history, last_metrics, oom_seen,
                    recorder,
                )
                if done:
                    break
            except Exception as exc:
                from ray_tpu import exceptions as core_exc

                recoverable = isinstance(
                    exc,
                    (
                        core_exc.GangDiedError,
                        core_exc.ActorDiedError,
                        core_exc.WorkerCrashedError,
                        TrainingFailedError,
                    ),
                )
                if not recoverable:
                    raise
                error = exc
            finally:
                prev_size = (
                    executor.gang.num_workers if executor.gang else None
                )
                executor.shutdown()

            if resize is not None:
                # Voluntary transition at a checkpoint boundary (grow-back
                # or preemptive drain): not a failure, not counted against
                # max_failures, no backoff.
                resizes.append(resize)
                latest_ckpt = storage.latest_checkpoint()
                continue
            if error is not None:
                # Wall clock since the last committed round is lost work +
                # detection latency: the "stalled" goodput bucket.
                recorder.note_stalled_since_progress()
                max_failures = run_cfg.failure_config.max_failures
                if run_cfg.failure_config.fail_fast or (
                    0 <= max_failures <= failures
                ):
                    break
                failures += 1
                resizes.append(
                    {"reason": "gang_died", "from": prev_size, "to": None}
                )
                latest_ckpt = storage.latest_checkpoint()
                error = None
                sleep_t0 = time.monotonic()
                backoff.sleep()
                recorder.note_restart(time.monotonic() - sleep_t0)
                continue
            break

        return Result(
            metrics=last_metrics,
            checkpoint=storage.best_checkpoint(),
            path=storage.trial_dir,
            error=error,
            metrics_history=history,
            resizes=resizes,
            goodput=recorder.finalize(),
        )

    # -- elasticity probes (evaluated at checkpoint boundaries) ----------
    def _want_grow(self, executor: BackendExecutor, state: dict) -> bool:
        """Capacity probe: can the gang grow back toward num_workers?

        Throttled to elastic_grow_probe_period_s; a positive answer is
        best-effort (the re-formed gang steps down again if the capacity
        evaporated) but only fires when the cluster-wide free resources
        cover every missing bundle.
        """
        sc = self.scaling_config
        if not sc.elastic or sc.elastic_grow_probe_period_s <= 0:
            return False
        current = executor.gang.num_workers if executor.gang else 0
        missing = sc.total_workers - current
        if missing <= 0:
            return False
        now = time.monotonic()
        if now - state.get("last_probe", 0.0) < sc.elastic_grow_probe_period_s:
            return False
        state["last_probe"] = now
        try:
            import ray_tpu

            avail = ray_tpu.available_resources()
        except Exception:  # rtlint: disable=swallowed-exception - resource probe failed: skip this grow attempt
            return False
        need = self.scaling_config.worker_resources()
        return all(
            avail.get(res, 0.0) >= amt * missing for res, amt in need.items()
        )

    def _oom_flagged_ranks(
        self, executor: BackendExecutor, oom_seen: int
    ) -> tuple[list[int], int]:
        """New oom_risk telemetry events matched against gang nodes.

        Returns (flagged ranks, new high-water event count).
        """
        if not self.scaling_config.drain_on_oom_risk:
            return [], oom_seen
        session_dir = _session_events_dir_known()
        if not session_dir:
            return [], oom_seen
        try:
            from ray_tpu._private.event_export import read_events

            events = read_events(session_dir, "oom_risk")
        except Exception:
            return [], oom_seen
        fresh = events[oom_seen:]
        if not fresh:
            return [], oom_seen
        try:
            infos = executor.gang.rank_infos()
        except Exception:
            return [], len(events)
        node_to_rank = {info["node_id"]: info["rank"] for info in infos}
        flagged = sorted(
            {
                node_to_rank[ev["data"]["node_id"]]
                for ev in fresh
                if ev.get("data", {}).get("node_id") in node_to_rank
            }
        )
        return flagged, len(events)

    def _drive(
        self,
        executor: BackendExecutor,
        storage: StorageContext,
        history: list,
        last_metrics: dict,
        oom_seen: int = 0,
        recorder=None,
    ) -> tuple[bool, dict, Exception | None, dict | None, int]:
        """Poll rounds until every rank is done, an error surfaces, a stop
        criterion is met, or a checkpoint boundary triggers a voluntary
        resize. Returns (done, last_metrics, error, resize, oom_seen)."""
        stop = self.run_config.stop or {}
        probe_state: dict = {}
        while True:
            round_results = executor.poll_round()
            errors = [r for r in round_results if "error" in r]
            if errors:
                err = errors[0]["error"]
                err.worker_traceback = errors[0].get("traceback", "")  # type: ignore
                return True, last_metrics, err, None, oom_seen
            if all(r.get("done") for r in round_results):
                return True, last_metrics, None, None, oom_seen
            reports = [r for r in round_results if "metrics" in r]
            if not reports:
                continue
            metrics = dict(reports[0]["metrics"])
            # Flight recorder (ISSUE 8): fold every rank's StepStats into
            # the rolling gang view; surface throughput + stragglers in
            # the user-visible metrics stream.
            if recorder is not None:
                step_summary = recorder.on_round(round_results)
                if step_summary:
                    metrics.setdefault(
                        "tokens_per_s", step_summary["tokens_per_s"]
                    )
                    if step_summary.get("mfu") is not None:
                        metrics.setdefault("mfu", step_summary["mfu"])
                    if recorder.stragglers:
                        ranks = [s["rank"] for s in recorder.stragglers]
                        metrics["stragglers"] = ranks
                        if probe_state.get("stragglers_logged") != ranks:
                            probe_state["stragglers_logged"] = ranks
                            logger.warning(
                                "straggling ranks detected: %s",
                                recorder.stragglers,
                            )
            # Surface which collective backend the gang actually runs
            # (acceptance: the hier auto-upgrade must be observable from
            # Result.metrics without user code changes).
            metrics.setdefault(
                "collective_backend", executor.selected_backend
            )
            # Stamp the (dp, fsdp, tp, pp) factorization this run chose
            # (ISSUE 10). Worker loops that know better (e.g. a mesh
            # built over all local devices) report their own value and
            # win the setdefault.
            metrics.setdefault(
                "factorization", self.scaling_config.factorization()
            )
            ckpt = executor.merge_sharded_checkpoints(
                [r.get("checkpoint") for r in round_results]
            )
            committed = False
            if ckpt is not None:
                world = executor.gang.num_workers
                ingest_states = [r.get("ingest") for r in round_results]
                ingest = None
                if any(ingest_states):
                    names = {
                        n for s in ingest_states if s for n in s
                    }
                    ingest = {
                        "world_size": world,
                        "datasets": {
                            name: [
                                (s or {}).get(name) for s in ingest_states
                            ]
                            for name in names
                        },
                    }
                persist_t0 = time.monotonic()
                try:
                    persisted = storage.persist(ckpt, metrics, ingest=ingest)
                except IOError as exc:
                    # Torn sharded save (a writer's marker or inventory is
                    # missing): skip the commit, keep training — recovery
                    # falls back to the previous committed checkpoint.
                    logger.warning("skipping uncommittable checkpoint: %s", exc)
                else:
                    metrics["checkpoint_path"] = persisted.path
                    committed = True
                finally:
                    if recorder is not None:
                        # Driver-side commit time is the checkpoint goodput
                        # bucket (spent either way, committed or torn).
                        recorder.note_checkpoint(
                            time.monotonic() - persist_t0
                        )
            last_metrics = metrics
            history.append(metrics)
            for cb in self.run_config.callbacks:
                handler = getattr(cb, "on_result", None)
                if handler:
                    handler(metrics)
            if any(
                key in metrics and metrics[key] >= bound
                for key, bound in stop.items()
            ):
                return True, last_metrics, None, None, oom_seen
            if committed:
                # Checkpoint boundary: the only safe place for voluntary
                # transitions (nothing since the commit is lost).
                flagged, oom_seen = self._oom_flagged_ranks(
                    executor, oom_seen
                )
                cur = executor.gang.num_workers
                if flagged:
                    logger.warning(
                        "oom_risk flagged gang ranks %s; preemptive "
                        "checkpoint-and-replace", flagged,
                    )
                    return False, last_metrics, None, {
                        "reason": "oom_risk_drain",
                        "from": cur,
                        "to": None,
                        "ranks": flagged,
                    }, oom_seen
                if self._want_grow(executor, probe_state):
                    return False, last_metrics, None, {
                        "reason": "grow",
                        "from": cur,
                        "to": self.scaling_config.total_workers,
                    }, oom_seen


class JaxTrainer(DataParallelTrainer):
    """The flagship trainer. Same driver loop as DataParallelTrainer; the
    jax-specific machinery (mesh construction, param sharding, in-jit
    collectives, sharded checkpoints) lives in ray_tpu.train.jax_utils and
    runs inside train_loop_per_worker.

    backend="xla" (default on real slices) assumes gang members joined one
    jax.distributed runtime — collectives happen inside jit on ICI.
    backend="ring" (tests / CPU) gives eager host-memory collectives.

    ``topology=`` (a parallel.topology.SliceTopology) declares a
    multi-slice layout — cross-slice DCN axes composed with in-slice ICI
    axes; it reaches the workers via the train context
    (get_context().slice_topology → jax_utils.build_mesh(topology=...)).
    Implies the xla backend: the gang shares one jax.distributed runtime
    whose processes span the slices.
    """

    _default_backend = "ring"

    def __init__(self, *args, topology=None, **kwargs):
        super().__init__(*args, **kwargs)
        if topology is not None:
            self.scaling_config.slice_topology = topology
        if (
            self.scaling_config.use_tpu
            or self.scaling_config.slice_topology is not None
        ) and kwargs.get("backend") is None:
            self.backend = "xla"

"""Checkpoint — directory + URI checkpoints with TPU-sharded pytree I/O.

Role-equivalent of python/ray/train/_checkpoint.py :: Checkpoint (a directory
with no format opinions), plus what the reference leaves to orbax/tensorstore
(SURVEY §5.4 TPU-equiv): **sharded** pytree save/restore — each host writes
only its addressable shards, a manifest records the global shapes and mesh
metadata, and restore can re-shard onto a different mesh (load a v4-32
checkpoint onto a v4-16) because shard files carry their global index.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import re
import shutil
import tempfile
import uuid
from typing import Any, Iterator

import numpy as np

_MANIFEST = "manifest.json"
_TREEDEF = "treedef.pkl"


class Checkpoint:
    """A directory of files; the framework never interprets the contents
    except through the pytree helpers below."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: str | None = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self) -> int:
        return hash(self.path)


# ---------------------------------------------------------------------------
# Sharded pytree I/O
# ---------------------------------------------------------------------------

def _leaf_key(path_parts: tuple) -> str:
    import jax.tree_util as jtu

    out = []
    for p in path_parts:
        if isinstance(p, jtu.DictKey):
            out.append(str(p.key))
        elif isinstance(p, jtu.SequenceKey):
            out.append(str(p.idx))
        elif isinstance(p, jtu.GetAttrKey):
            out.append(str(p.name))
        else:
            out.append(str(p))
    return ".".join(out) or "leaf"


def save_pytree(
    directory: str,
    tree: Any,
    *,
    process_index: int = 0,
    mesh_metadata: dict | None = None,
) -> None:
    """Write this process's addressable shards of a (possibly sharded) jax
    pytree under `directory`.

    Layout:
      manifest.json                  — global shapes/dtypes + mesh metadata
                                       (written by process 0)
      treedef.pkl                    — pickled treedef (process 0)
      shards/p<proc>/<leaf>.s<k>.npy — one file per addressable shard
      shards/p<proc>/<leaf>.s<k>.idx.json — its global index (start/stop per dim)

    Every process calls this with the same tree; on shared storage the union
    of shard files covers every global array exactly once per replica (we
    only write shards whose replica_id == 0, so replicated leaves are written
    once cluster-wide).
    """
    import jax
    import jax.tree_util as jtu

    leaves_with_paths, treedef = jtu.tree_flatten_with_path(tree)
    shard_dir = os.path.join(directory, "shards", f"p{process_index}")
    os.makedirs(shard_dir, exist_ok=True)

    manifest: dict[str, Any] = {"leaves": {}, "mesh": mesh_metadata or {}}
    for path_parts, leaf in leaves_with_paths:
        key = _leaf_key(path_parts)
        if isinstance(leaf, jax.Array):
            manifest["leaves"][key] = {
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
            }
            for k, shard in enumerate(leaf.addressable_shards):
                if shard.replica_id != 0:
                    continue
                data = np.asarray(shard.data)
                np.save(os.path.join(shard_dir, f"{key}.s{k}.npy"), data)
                index = [
                    [s.start or 0, s.stop if s.stop is not None else dim]
                    for s, dim in zip(shard.index, leaf.shape)
                ]
                with open(
                    os.path.join(shard_dir, f"{key}.s{k}.idx.json"), "w"
                ) as f:
                    json.dump(index, f)
        else:
            manifest["leaves"][key] = {"scalar": True}
            if process_index == 0:
                with open(os.path.join(shard_dir, f"{key}.scalar.pkl"), "wb") as f:
                    pickle.dump(leaf, f)

    if process_index == 0:
        with open(os.path.join(directory, _TREEDEF), "wb") as f:
            pickle.dump(treedef, f)
        tmp = os.path.join(directory, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(directory, _MANIFEST))


def load_pytree(directory: str, shardings: Any | None = None) -> Any:
    """Assemble global arrays from shard files and (optionally) place them
    with `shardings` (a pytree of jax shardings matching the saved tree) —
    this is the resharding-restore path: the target mesh need not match the
    mesh that wrote the checkpoint."""
    import jax
    import jax.tree_util as jtu

    with open(os.path.join(directory, _MANIFEST)) as f:
        manifest = json.load(f)
    with open(os.path.join(directory, _TREEDEF), "rb") as f:
        treedef = pickle.load(f)

    shards_root = os.path.join(directory, "shards")
    proc_dirs = sorted(os.listdir(shards_root)) if os.path.isdir(shards_root) else []

    arrays: dict[str, Any] = {}
    for key, meta in manifest["leaves"].items():
        if meta.get("scalar"):
            for pd in proc_dirs:
                p = os.path.join(shards_root, pd, f"{key}.scalar.pkl")
                if os.path.exists(p):
                    with open(p, "rb") as f:
                        arrays[key] = pickle.load(f)
                    break
            else:
                arrays[key] = None
            continue
        out = np.empty(meta["shape"], dtype=np.dtype(meta["dtype"]))
        filled = np.zeros(meta["shape"], dtype=bool) if meta["shape"] else None
        for pd in proc_dirs:
            pdir = os.path.join(shards_root, pd)
            shard_re = re.compile(re.escape(key) + r"\.s\d+\.npy$")
            for fname in os.listdir(pdir):
                # Exact-key match: plain prefix tests would let a leaf named
                # "w.step" feed shards into leaf "w".
                if not shard_re.fullmatch(fname):
                    continue
                data = np.load(os.path.join(pdir, fname))
                with open(os.path.join(pdir, fname[:-4] + ".idx.json")) as f:
                    index = json.load(f)
                slices = tuple(slice(a, b) for a, b in index)
                out[slices] = data
                if filled is not None:
                    filled[slices] = True
        if filled is not None and not filled.all():
            raise IOError(
                f"checkpoint {directory}: leaf {key} has missing shards "
                f"({int((~filled).sum())} elements uncovered)"
            )
        arrays[key] = out

    leaves_with_paths, _ = jtu.tree_flatten_with_path(
        jtu.tree_unflatten(treedef, [0] * treedef.num_leaves)
    )
    ordered = [arrays[_leaf_key(p)] for p, _ in leaves_with_paths]
    tree = jtu.tree_unflatten(treedef, ordered)
    if shardings is not None:
        tree = jax.tree.map(
            lambda x, s: jax.device_put(x, s) if isinstance(x, np.ndarray) else x,
            tree,
            shardings,
        )
    return tree


def save_pytree_checkpoint(tree: Any, *, extra: dict | None = None) -> Checkpoint:
    """Convenience: materialize a pytree (plus pickled `extra` metadata) as a
    fresh local Checkpoint directory."""
    path = os.path.join(
        tempfile.gettempdir(), f"ray_tpu_ckpt_{uuid.uuid4().hex[:8]}"
    )
    os.makedirs(path, exist_ok=True)
    save_pytree(path, tree)
    if extra is not None:
        with open(os.path.join(path, "extra.pkl"), "wb") as f:
            pickle.dump(extra, f)
    return Checkpoint(path)


def load_pytree_checkpoint(
    checkpoint: Checkpoint, shardings: Any | None = None
) -> tuple[Any, dict]:
    with checkpoint.as_directory() as path:
        tree = load_pytree(path, shardings)
        extra_path = os.path.join(path, "extra.pkl")
        extra = {}
        if os.path.exists(extra_path):
            with open(extra_path, "rb") as f:
                extra = pickle.load(f)
    return tree, extra

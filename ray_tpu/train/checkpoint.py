"""Checkpoint — directory + URI checkpoints with TPU-sharded pytree I/O.

Role-equivalent of python/ray/train/_checkpoint.py :: Checkpoint (a directory
with no format opinions), plus what the reference leaves to orbax/tensorstore
(SURVEY §5.4 TPU-equiv): **sharded** pytree save/restore — each host writes
only its addressable shards, a manifest records the global shapes and mesh
metadata, and restore can re-shard onto a different mesh (load a v4-32
checkpoint onto a v4-16) because shard files carry their global index.

Commit protocol (ISSUE 6): a sharded save is *two-phase*. Every writer rank
drops a ``DONE.p<rank>`` marker — an inventory of the files it wrote with
sizes and CRCs — only after all its shard files are on disk, and every
small file goes through tmp + ``os.replace``. A checkpoint directory is
*complete* when every ``shards/p<rank>`` dir has a matching, verifying
``DONE.p<rank>``; ``StorageContext.persist`` stages, verifies, stamps a
``COMMIT.json`` and atomically renames — so a SIGKILL anywhere between
shard write and commit can only ever leave a directory that readers skip,
never a loadable-but-wrong checkpoint.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import re
import shutil
import tempfile
import time
import uuid
import zlib
from typing import Any, Iterator

import numpy as np

from ray_tpu._private import atomic_io

_MANIFEST = "manifest.json"
_TREEDEF = "treedef.pkl"
_COMMIT = "COMMIT.json"
_DONE_PREFIX = "DONE.p"


class Checkpoint:
    """A directory of files; the framework never interprets the contents
    except through the pytree helpers below."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: str | None = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self) -> int:
        return hash(self.path)


# ---------------------------------------------------------------------------
# Atomic small-file writes
# ---------------------------------------------------------------------------

# tmp + os.replace so a crash mid-write never leaves a torn file at the
# final name (readers either see the old content or the new). The
# canonical implementation moved to ray_tpu._private.atomic_io so every
# state-writing layer shares it; these aliases keep the historical names
# that the rest of the train package (and backend_executor) import.
_atomic_write_bytes = atomic_io.atomic_write_bytes
_atomic_write_json = atomic_io.atomic_write_json
_atomic_write_pickle = atomic_io.atomic_write_pickle


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


# ---------------------------------------------------------------------------
# Sharded pytree I/O
# ---------------------------------------------------------------------------

# Separator / path chars that must never leak into a leaf key: "." is the
# key-path join char (a dict key "a.b" would collide with nested {"a":
# {"b": ...}}), "/" and NUL would break shard file paths. "%" escapes the
# escape char itself so the mapping is injective.
_KEY_ESCAPES = {"%": "%25", ".": "%2E", "/": "%2F", "\\": "%5C", "\x00": "%00"}


def _escape_key_part(part: str) -> str:
    if not any(ch in part for ch in _KEY_ESCAPES):
        return part
    return "".join(_KEY_ESCAPES.get(ch, ch) for ch in part)


def _leaf_key(path_parts: tuple) -> str:
    import jax.tree_util as jtu

    out = []
    for p in path_parts:
        if isinstance(p, jtu.DictKey):
            out.append(_escape_key_part(str(p.key)))
        elif isinstance(p, jtu.SequenceKey):
            out.append(str(p.idx))
        elif isinstance(p, jtu.GetAttrKey):
            out.append(_escape_key_part(str(p.name)))
        else:
            out.append(_escape_key_part(str(p)))
    return ".".join(out) or "leaf"


def _done_marker_path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"{_DONE_PREFIX}{process_index}")


def save_pytree(
    directory: str,
    tree: Any,
    *,
    process_index: int = 0,
    world_size: int = 1,
    mesh_metadata: dict | None = None,
) -> None:
    """Write this process's addressable shards of a (possibly sharded) jax
    pytree under `directory`, two-phase.

    Layout:
      manifest.json                  — global shapes/dtypes + mesh metadata
                                       + writer world size (process 0)
      treedef.pkl                    — pickled treedef (process 0)
      shards/p<proc>/<leaf>.s<k>.npy — one file per addressable shard
      shards/p<proc>/<leaf>.s<k>.idx.json — its global index (start/stop per dim)
      DONE.p<proc>                   — commit marker: inventory of every file
                                       this rank wrote (relpath → size, crc32),
                                       written LAST and atomically

    Every process calls this with the same tree; on shared storage the union
    of shard files covers every global array exactly once per replica (we
    only write shards whose replica_id == 0, so replicated leaves are written
    once cluster-wide). A reader must treat a shard dir without a verifying
    DONE marker as torn (``verify_sharded_checkpoint``).
    """
    import jax
    import jax.tree_util as jtu

    from ray_tpu.util import chaos

    _save_t0 = time.perf_counter()
    leaves_with_paths, treedef = jtu.tree_flatten_with_path(tree)
    # Collision guard: escaping makes key construction injective, but a
    # tree could still produce duplicate keys through exotic custom nodes —
    # refuse at save time rather than silently merging two leaves' shards.
    seen: dict[str, tuple] = {}
    for path_parts, _leaf in leaves_with_paths:
        key = _leaf_key(path_parts)
        if key in seen and seen[key] != path_parts:
            raise ValueError(
                f"leaf key collision: tree paths {seen[key]!r} and "
                f"{path_parts!r} both map to shard key {key!r}"
            )
        seen[key] = path_parts

    shard_dir = os.path.join(directory, "shards", f"p{process_index}")
    os.makedirs(shard_dir, exist_ok=True)
    # relpath (from `directory`) → {"size": bytes, "crc32": int}
    inventory: dict[str, dict] = {}

    def _track(path: str) -> None:
        rel = os.path.relpath(path, directory)
        inventory[rel] = {
            "size": os.path.getsize(path), "crc32": _file_crc32(path)
        }

    manifest: dict[str, Any] = {
        "leaves": {},
        "mesh": mesh_metadata or {},
        "world_size": int(world_size),
    }
    for path_parts, leaf in leaves_with_paths:
        key = _leaf_key(path_parts)
        if isinstance(leaf, jax.Array):
            manifest["leaves"][key] = {
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
            }
            for k, shard in enumerate(leaf.addressable_shards):
                if shard.replica_id != 0:
                    continue
                data = np.asarray(shard.data)
                npy_path = os.path.join(shard_dir, f"{key}.s{k}.npy")
                tmp = f"{npy_path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    np.save(f, data)
                os.replace(tmp, npy_path)
                _track(npy_path)
                index = [
                    [s.start or 0, s.stop if s.stop is not None else dim]
                    for s, dim in zip(shard.index, leaf.shape)
                ]
                idx_path = os.path.join(shard_dir, f"{key}.s{k}.idx.json")
                _atomic_write_json(idx_path, index)
                _track(idx_path)
        else:
            manifest["leaves"][key] = {"scalar": True}
            if process_index == 0:
                pkl_path = os.path.join(shard_dir, f"{key}.scalar.pkl")
                _atomic_write_pickle(pkl_path, leaf)
                _track(pkl_path)

    if process_index == 0:
        _atomic_write_pickle(os.path.join(directory, _TREEDEF), treedef)
        _track(os.path.join(directory, _TREEDEF))
        # The manifest is deliberately NOT inventoried: merge rewrites its
        # world_size to the actual writer count, and it is protected by its
        # own atomic write + the COMMIT stamp.
        _atomic_write_json(os.path.join(directory, _MANIFEST), manifest)

    # The torn-save window under proof: everything above is on disk but the
    # commit marker is not. A kill here must leave a checkpoint that
    # verify_sharded_checkpoint rejects and latest_checkpoint() skips.
    chaos.failpoint("train.checkpoint.mid_save")

    _atomic_write_json(
        _done_marker_path(directory, process_index),
        {"rank": int(process_index), "files": inventory},
    )
    # Flight recorder (ISSUE 8): a committed save's wall time is the
    # step's "checkpoint" phase (no-op outside an active train session).
    from ray_tpu.train._internal import step_stats

    step_stats.record_phase("checkpoint", time.perf_counter() - _save_t0)


def _done_markers(directory: str) -> dict[int, dict]:
    """rank → parsed DONE marker for every marker present in `directory`."""
    markers: dict[int, dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return markers
    for name in names:
        if not name.startswith(_DONE_PREFIX):
            continue
        suffix = name[len(_DONE_PREFIX):]
        if not suffix.isdigit():
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                markers[int(suffix)] = json.load(f)
        except (OSError, ValueError):
            continue
    return markers


def verify_sharded_checkpoint(directory: str) -> tuple[bool, str]:
    """Is this directory a *complete* sharded save?

    Rules:
      * no manifest.json → opaque user directory, nothing to verify → OK;
      * manifest present → treedef must parse, every ``shards/p<r>`` dir
        must have a DONE.p<r> marker, the marker count must cover the
        manifest's world size, and every inventoried file must exist with
        the recorded size and CRC.

    Returns (ok, reason) — reason describes the first failure found.
    """
    manifest_path = os.path.join(directory, _MANIFEST)
    if not os.path.exists(manifest_path):
        return True, "opaque (no manifest)"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        return False, f"unreadable manifest: {exc}"
    if not os.path.exists(os.path.join(directory, _TREEDEF)):
        return False, "missing treedef.pkl"

    markers = _done_markers(directory)
    shards_root = os.path.join(directory, "shards")
    shard_ranks = set()
    if os.path.isdir(shards_root):
        for name in os.listdir(shards_root):
            if name.startswith("p") and name[1:].isdigit():
                shard_ranks.add(int(name[1:]))
    for rank in sorted(shard_ranks):
        if rank not in markers:
            return False, f"shards/p{rank} present but DONE.p{rank} missing"
    world_size = int(manifest.get("world_size", 1) or 1)
    for rank in range(world_size):
        if rank not in markers:
            return False, (
                f"manifest world_size={world_size} but DONE.p{rank} missing"
            )
    for rank, marker in sorted(markers.items()):
        for rel, meta in (marker.get("files") or {}).items():
            path = os.path.join(directory, rel)
            if not os.path.exists(path):
                return False, f"inventoried file missing: {rel} (rank {rank})"
            size = os.path.getsize(path)
            if size != int(meta.get("size", -1)):
                return False, (
                    f"size mismatch for {rel}: {size} != {meta.get('size')}"
                )
            if "crc32" in meta and _file_crc32(path) != int(meta["crc32"]):
                return False, f"crc mismatch for {rel}"
    return True, "ok"


def is_committed(directory: str) -> bool:
    """True when the directory carries a parseable COMMIT.json stamp
    (written by StorageContext.persist after inventory verification)."""
    try:
        with open(os.path.join(directory, _COMMIT)) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def load_pytree(directory: str, shardings: Any | None = None) -> Any:
    """Assemble global arrays from shard files and (optionally) place them
    with `shardings` (a pytree of jax shardings matching the saved tree) —
    this is the resharding-restore path: the target mesh need not match the
    mesh that wrote the checkpoint. Validates the per-rank shard inventory
    before assembling anything, so a torn save fails fast instead of
    producing a silently wrong tree."""
    import jax
    import jax.tree_util as jtu

    ok, reason = verify_sharded_checkpoint(directory)
    if not ok:
        raise IOError(
            f"checkpoint {directory} failed inventory verification: {reason}"
        )

    with open(os.path.join(directory, _MANIFEST)) as f:
        manifest = json.load(f)
    with open(os.path.join(directory, _TREEDEF), "rb") as f:
        treedef = pickle.load(f)

    shards_root = os.path.join(directory, "shards")
    proc_dirs = sorted(os.listdir(shards_root)) if os.path.isdir(shards_root) else []

    arrays: dict[str, Any] = {}
    for key, meta in manifest["leaves"].items():
        if meta.get("scalar"):
            for pd in proc_dirs:
                p = os.path.join(shards_root, pd, f"{key}.scalar.pkl")
                if os.path.exists(p):
                    with open(p, "rb") as f:
                        arrays[key] = pickle.load(f)
                    break
            else:
                arrays[key] = None
            continue
        out = np.empty(meta["shape"], dtype=np.dtype(meta["dtype"]))
        filled = np.zeros(meta["shape"], dtype=bool) if meta["shape"] else None
        for pd in proc_dirs:
            pdir = os.path.join(shards_root, pd)
            shard_re = re.compile(re.escape(key) + r"\.s\d+\.npy$")
            for fname in os.listdir(pdir):
                # Exact-key match: plain prefix tests would let a leaf named
                # "w.step" feed shards into leaf "w".
                if not shard_re.fullmatch(fname):
                    continue
                data = np.load(os.path.join(pdir, fname))
                with open(os.path.join(pdir, fname[:-4] + ".idx.json")) as f:
                    index = json.load(f)
                slices = tuple(slice(a, b) for a, b in index)
                out[slices] = data
                if filled is not None:
                    filled[slices] = True
        if filled is not None and not filled.all():
            raise IOError(
                f"checkpoint {directory}: leaf {key} has missing shards "
                f"({int((~filled).sum())} elements uncovered)"
            )
        arrays[key] = out

    leaves_with_paths, _ = jtu.tree_flatten_with_path(
        jtu.tree_unflatten(treedef, [0] * treedef.num_leaves)
    )
    ordered = [arrays[_leaf_key(p)] for p, _ in leaves_with_paths]
    tree = jtu.tree_unflatten(treedef, ordered)
    if shardings is not None:
        tree = jax.tree.map(
            lambda x, s: jax.device_put(x, s) if isinstance(x, np.ndarray) else x,
            tree,
            shardings,
        )
    return tree


def save_pytree_checkpoint(tree: Any, *, extra: dict | None = None) -> Checkpoint:
    """Convenience: materialize a pytree (plus pickled `extra` metadata) as a
    fresh local Checkpoint directory. Inside a train session the writer
    identity (rank / world size) is stamped automatically so multi-rank
    sharded saves carry per-rank commit markers."""
    path = os.path.join(
        tempfile.gettempdir(), f"ray_tpu_ckpt_{uuid.uuid4().hex[:8]}"
    )
    os.makedirs(path, exist_ok=True)
    process_index, world_size = 0, 1
    from ray_tpu.train._internal import session as _session_mod

    if _session_mod.in_session():
        ctx = _session_mod.get_session().ctx
        process_index, world_size = ctx.world_rank, ctx.world_size
    save_pytree(
        path, tree, process_index=process_index, world_size=world_size
    )
    if extra is not None:
        _atomic_write_pickle(os.path.join(path, "extra.pkl"), extra)
    return Checkpoint(path)


def load_pytree_checkpoint(
    checkpoint: Checkpoint, shardings: Any | None = None
) -> tuple[Any, dict]:
    with checkpoint.as_directory() as path:
        tree = load_pytree(path, shardings)
        extra_path = os.path.join(path, "extra.pkl")
        extra = {}
        if os.path.exists(extra_path):
            with open(extra_path, "rb") as f:
                extra = pickle.load(f)
    return tree, extra

"""Device mesh + logical sharding vocabulary.

This is the heart of the TPU-first design (SURVEY §2.9): every parallelism
strategy the reference delegates to third-party engines (DeepSpeed/Megatron)
is a named axis of ONE jax mesh here:

    dp    — data parallel (batch split; gradients psum over dp)
    fsdp  — fully-sharded data parallel (params/opt-state sharded; ZeRO-3
            equivalent falls out of NamedSharding + pjit)
    tp    — tensor parallel (embed/mlp/heads split; matmul partials psum
            over ICI neighbors)
    sp    — sequence/context parallel (ring attention / Ulysses all_to_all)
    pp    — pipeline parallel (stage axis, ppermute microbatch hand-off)
    ep    — expert parallel (MoE expert sharding, all_to_all token routing)

Model code annotates arrays with *logical* dim names ("batch", "embed", ...);
`LogicalRules` maps logical names to mesh axes, giving one switchboard where
a whole model's sharding is reconfigured without touching model code (the
flax `logical_axis_rules` idea, rebuilt standalone).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")

# Default logical-dim -> mesh-axis rules (overridable per model/run).
DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp")),   # batch splits over both data axes
    ("seq", "sp"),               # sequence/context parallelism
    ("embed", "fsdp"),           # param sharding for ZeRO-style FSDP
    ("mlp", "tp"),               # feed-forward hidden dim over tensor axis
    ("heads", "tp"),             # attention heads over tensor axis
    ("kv", None),                # head_dim stays replicated
    ("vocab", "tp"),
    ("expert", "ep"),
    ("stage", "pp"),
)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name -> size. Order fixed by AXES so
    collective-heavy axes (tp/sp) land on the innermost (fastest, ICI-
    adjacent) mesh dimensions — the scaling-book layout recipe."""

    axes: dict[str, int]

    def __post_init__(self):
        for name in self.axes:
            if name not in AXES:
                raise ValueError(f"unknown mesh axis {name!r}; valid: {AXES}")
        if any(v <= 0 for v in self.axes.values()):
            raise ValueError("axis sizes must be positive")

    @property
    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def axis_names(self) -> tuple[str, ...]:
        """All declared axes (size-1 included: a PartitionSpec may name any
        declared axis; dropping trivial axes would break those consumers)."""
        return tuple(a for a in AXES if a in self.axes) or ("dp",)

    def build(self, devices: Sequence[Any] | None = None) -> Mesh:
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < self.size:
            raise ValueError(
                f"mesh needs {self.size} devices, have {len(devices)}"
            )
        names = self.axis_names()
        shape = tuple(self.axes.get(a, 1) for a in names)
        if math.prod(shape) == 0:
            shape = (1,)
        grid = np.array(devices[: math.prod(shape)]).reshape(shape)
        return Mesh(grid, names)


class LogicalRules:
    """Maps logical dim names to mesh axes and builds shardings."""

    def __init__(self, rules: Sequence[tuple[str, Any]] = DEFAULT_RULES):
        self._rules = dict(rules)

    def with_overrides(self, **overrides: Any) -> "LogicalRules":
        merged = dict(self._rules)
        merged.update(overrides)
        return LogicalRules(tuple(merged.items()))

    def spec(self, logical_dims: Sequence[str | None], mesh: Mesh) -> P:
        """PartitionSpec for an array whose dims carry these logical names.
        Mesh axes not present in the mesh (size 1 / absent) degrade to
        replication, so one set of annotations serves every mesh shape."""
        entries = []
        used: set[str] = set()
        for dim in logical_dims:
            if dim is None:
                entries.append(None)
                continue
            axis = self._rules.get(dim)
            if axis is None:
                entries.append(None)
                continue
            if isinstance(axis, (tuple, list)):
                present = tuple(
                    a for a in axis if a in mesh.axis_names and a not in used
                )
                used.update(present)
                entries.append(present if present else None)
            else:
                if axis in mesh.axis_names and axis not in used:
                    used.add(axis)
                    entries.append(axis)
                else:
                    entries.append(None)
        return P(*entries)

    def sharding(
        self, logical_dims: Sequence[str | None], mesh: Mesh
    ) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_dims, mesh))

    def tree_shardings(
        self, logical_tree: Any, mesh: Mesh
    ) -> Any:
        """Map a pytree of logical-dim tuples to a pytree of NamedShardings."""
        return jax.tree.map(
            lambda dims: self.sharding(dims, mesh),
            logical_tree,
            is_leaf=lambda x: isinstance(x, (tuple, list))
            and all(isinstance(d, (str, type(None))) for d in x),
        )


@dataclasses.dataclass(frozen=True)
class LogicalSpec:
    """Explicit per-leaf logical-dim annotation (an alternative to raw
    tuples inside a logical tree): ``LogicalSpec("embed", "mlp")`` names
    the logical dims of a 2-D leaf. Useful where a bare tuple would be
    swallowed as pytree structure (e.g. dataclass model configs)."""

    dims: tuple

    def __init__(self, *dims: str | None):
        object.__setattr__(self, "dims", tuple(dims))

    def __iter__(self):
        return iter(self.dims)

    def __len__(self) -> int:
        return len(self.dims)


def _is_logical_leaf(x: Any) -> bool:
    if isinstance(x, LogicalSpec):
        return True
    return isinstance(x, (tuple, list)) and all(
        isinstance(d, (str, type(None))) for d in x
    )


def _axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape[axis]) if axis in mesh.axis_names else 1


def fsdp_extend_spec(
    shape: Sequence[int], base: P, mesh: Mesh, axis: str = "fsdp"
) -> P:
    """The FSDP auto-policy: *shard-largest-axis*.

    Starting from ``base`` (usually the TP spec derived from logical
    dims), shard the LARGEST still-unsharded array dim over ``axis`` —
    the ZeRO-3 move that divides param/grad/opt-state residency by the
    fsdp factor without model annotations. Rules:

      * ``axis`` absent from the mesh (or size 1) → no-op;
      * ``axis`` already used by ``base`` → no-op (never reuse a mesh
        axis within one array);
      * scalars and 1-D leaves stay replicated — they are norm scales /
        step counters; sharding them buys ~nothing and costs a gather;
      * only dims whose size divides evenly by the fsdp factor are
        candidates (GSPMD would pad uneven shards — surprise memory);
      * among candidates, the largest dim wins (ties → leading dim).
    """
    ndim = len(shape)
    entries = list(base) + [None] * (ndim - len(base))
    used: set[str] = set()
    for e in entries:
        for a in (e if isinstance(e, (tuple, list)) else (e,)):
            if a is not None:
                used.add(a)
    size = _axis_size(mesh, axis)
    if size <= 1 or axis in used or ndim < 2:
        return P(*entries) if entries else P()
    candidates = [
        d
        for d in range(ndim)
        if entries[d] is None and shape[d] > 1 and shape[d] % size == 0
    ]
    if not candidates:
        return P(*entries)
    best = max(candidates, key=lambda d: (shape[d], -d))
    entries[best] = axis
    return P(*entries)


def transformer_tp_rules() -> LogicalRules:
    """The tensor-parallel policy for the flagship transformer's
    attention/MLP blocks (models/transformer.py): Megatron-style column
    split on wq/wk/wv + w_gate/w_up ("heads"/"mlp" → tp) and row split
    on wo/w_down ("heads"/"mlp" on the *input* dim → tp), with the
    embedding table split over vocab. These ARE the defaults; this
    constructor exists so callers can start from the canonical TP
    mapping and override per run (e.g. sequence-parallel overlays)."""
    return LogicalRules(DEFAULT_RULES)


def auto_shard_specs(
    tree: Any,
    mesh: Mesh,
    *,
    logical_dims: Any = None,
    rules: LogicalRules | None = None,
    fsdp_axis: str = "fsdp",
) -> Any:
    """Per-leaf NamedShardings for a whole state pytree, from ONE mesh.

    Composition order is the GSPMD training recipe:

      1. ``logical_dims`` (a pytree of logical-dim tuples matching
         ``tree``, e.g. models.transformer.param_logical_dims) maps TP/
         EP/vocab dims onto mesh axes via ``rules``;
      2. the FSDP *shard-largest-axis* auto-policy (see
         :func:`fsdp_extend_spec`) then shards the largest remaining dim
         of every ≥2-D leaf over ``fsdp_axis``.

    Axes absent from the mesh degrade to replication, so the same call
    serves every factorization — a pure-dp mesh returns fully
    replicated specs (the degenerate data-parallel case).

    ``tree`` may hold arrays or ``jax.ShapeDtypeStruct``s (plan before
    materializing — the fit-at-scale path shards *init* itself).
    """
    rules = rules or LogicalRules()

    def leaf_spec(leaf: Any, dims: Any) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or np.shape(leaf))
        if dims is not None:
            base = rules.spec(tuple(dims), mesh)
        else:
            base = P()
        return NamedSharding(mesh, fsdp_extend_spec(shape, base, mesh, fsdp_axis))

    if logical_dims is None:
        return jax.tree.map(lambda leaf: leaf_spec(leaf, None), tree)
    # Match annotations to leaves BY PATH, not by structure: real models
    # annotate the hot matmuls and leave the rest to the FSDP policy, so
    # a partial logical_dims dict must not be a structure error.
    dim_by_path = {
        path: dims
        for path, dims in jax.tree_util.tree_flatten_with_path(
            logical_dims, is_leaf=_is_logical_leaf
        )[0]
    }
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef,
        [leaf_spec(leaf, dim_by_path.get(path)) for path, leaf in leaves],
    )


def single_host_mesh(**axes: int) -> Mesh:
    """Convenience: build a mesh over this process's local devices."""
    return MeshSpec(axes).build(jax.local_devices())


def shard_batch(batch: Any, mesh: Mesh, rules: LogicalRules | None = None) -> Any:
    """device_put a host batch with its leading dim split over the data axes."""
    rules = rules or LogicalRules()

    def _put(x):
        dims = ["batch"] + [None] * (np.ndim(x) - 1)
        return jax.device_put(x, rules.sharding(dims, mesh))

    return jax.tree.map(_put, batch)

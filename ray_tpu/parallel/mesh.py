"""Device mesh + logical sharding vocabulary.

This is the heart of the TPU-first design (SURVEY §2.9): every parallelism
strategy the reference delegates to third-party engines (DeepSpeed/Megatron)
is a named axis of ONE jax mesh here:

    dp    — data parallel (batch split; gradients psum over dp)
    fsdp  — fully-sharded data parallel (params/opt-state sharded; ZeRO-3
            equivalent falls out of NamedSharding + pjit)
    tp    — tensor parallel (embed/mlp/heads split; matmul partials psum
            over ICI neighbors)
    sp    — sequence/context parallel (ring attention / Ulysses all_to_all)
    pp    — pipeline parallel (stage axis, ppermute microbatch hand-off)
    ep    — expert parallel (MoE expert sharding, all_to_all token routing)

Model code annotates arrays with *logical* dim names ("batch", "embed", ...);
`LogicalRules` maps logical names to mesh axes, giving one switchboard where
a whole model's sharding is reconfigured without touching model code (the
flax `logical_axis_rules` idea, rebuilt standalone).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")

# Default logical-dim -> mesh-axis rules (overridable per model/run).
DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp")),   # batch splits over both data axes
    ("seq", "sp"),               # sequence/context parallelism
    ("embed", "fsdp"),           # param sharding for ZeRO-style FSDP
    ("mlp", "tp"),               # feed-forward hidden dim over tensor axis
    ("heads", "tp"),             # attention heads over tensor axis
    ("kv", None),                # head_dim stays replicated
    ("vocab", "tp"),
    ("expert", "ep"),
    ("stage", "pp"),
)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name -> size. Order fixed by AXES so
    collective-heavy axes (tp/sp) land on the innermost (fastest, ICI-
    adjacent) mesh dimensions — the scaling-book layout recipe."""

    axes: dict[str, int]

    def __post_init__(self):
        for name in self.axes:
            if name not in AXES:
                raise ValueError(f"unknown mesh axis {name!r}; valid: {AXES}")
        if any(v <= 0 for v in self.axes.values()):
            raise ValueError("axis sizes must be positive")

    @property
    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def axis_names(self) -> tuple[str, ...]:
        """All declared axes (size-1 included: a PartitionSpec may name any
        declared axis; dropping trivial axes would break those consumers)."""
        return tuple(a for a in AXES if a in self.axes) or ("dp",)

    def build(self, devices: Sequence[Any] | None = None) -> Mesh:
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < self.size:
            raise ValueError(
                f"mesh needs {self.size} devices, have {len(devices)}"
            )
        names = self.axis_names()
        shape = tuple(self.axes.get(a, 1) for a in names)
        if math.prod(shape) == 0:
            shape = (1,)
        grid = np.array(devices[: math.prod(shape)]).reshape(shape)
        return Mesh(grid, names)


class LogicalRules:
    """Maps logical dim names to mesh axes and builds shardings."""

    def __init__(self, rules: Sequence[tuple[str, Any]] = DEFAULT_RULES):
        self._rules = dict(rules)

    def with_overrides(self, **overrides: Any) -> "LogicalRules":
        merged = dict(self._rules)
        merged.update(overrides)
        return LogicalRules(tuple(merged.items()))

    def spec(self, logical_dims: Sequence[str | None], mesh: Mesh) -> P:
        """PartitionSpec for an array whose dims carry these logical names.
        Mesh axes not present in the mesh (size 1 / absent) degrade to
        replication, so one set of annotations serves every mesh shape."""
        entries = []
        used: set[str] = set()
        for dim in logical_dims:
            if dim is None:
                entries.append(None)
                continue
            axis = self._rules.get(dim)
            if axis is None:
                entries.append(None)
                continue
            if isinstance(axis, (tuple, list)):
                present = tuple(
                    a for a in axis if a in mesh.axis_names and a not in used
                )
                used.update(present)
                entries.append(present if present else None)
            else:
                if axis in mesh.axis_names and axis not in used:
                    used.add(axis)
                    entries.append(axis)
                else:
                    entries.append(None)
        return P(*entries)

    def sharding(
        self, logical_dims: Sequence[str | None], mesh: Mesh
    ) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_dims, mesh))

    def tree_shardings(
        self, logical_tree: Any, mesh: Mesh
    ) -> Any:
        """Map a pytree of logical-dim tuples to a pytree of NamedShardings."""
        return jax.tree.map(
            lambda dims: self.sharding(dims, mesh),
            logical_tree,
            is_leaf=lambda x: isinstance(x, (tuple, list))
            and all(isinstance(d, (str, type(None))) for d in x),
        )


def single_host_mesh(**axes: int) -> Mesh:
    """Convenience: build a mesh over this process's local devices."""
    return MeshSpec(axes).build(jax.local_devices())


def shard_batch(batch: Any, mesh: Mesh, rules: LogicalRules | None = None) -> Any:
    """device_put a host batch with its leading dim split over the data axes."""
    rules = rules or LogicalRules()

    def _put(x):
        dims = ["batch"] + [None] * (np.ndim(x) - 1)
        return jax.device_put(x, rules.sharding(dims, mesh))

    return jax.tree.map(_put, batch)

"""Pipeline parallelism — GPipe-style microbatching over the `pp` mesh axis.

The reference expresses pipelines via compiled-graph NCCL channels between
actor stages (python/ray/dag/, SURVEY §2.9 PP row). TPU-native version:
stages live on a `pp` mesh axis; activations hop stage→stage with
`ppermute` inside ONE compiled program (lax.fori_loop over pipeline ticks),
so XLA overlaps the ICI hand-off with each stage's compute.

Layout: layer-stacked params get their leading "layer" dim sharded over pp
(each pp rank holds n_layers / pp_size consecutive layers). The schedule is
the classic (M + P - 1)-tick GPipe fill/drain loop.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel._compat import shard_map


# ---------------------------------------------------------------------------
# Microbatch scheduling (MPMD stages — the cross-slice pipeline)
# ---------------------------------------------------------------------------
#
# The in-program ppermute pipeline below is the single-slice form. Across
# pod slices the stages are SEPARATE programs on separate gang workers
# (MPMD — "Scaling Deep Learning Training with MPMD Pipeline Parallelism"),
# and the schedule is host-side data each stage runner executes, with p2p
# activation hand-offs providing the cross-stage ordering. The scheduler
# here is pure math (no jax) so the driver, the stage runner, and the
# release gate all share one bubble model.

def schedule_1f1b(
    num_stages: int, num_microbatches: int, stage: int
) -> list[tuple[str, int]]:
    """This stage's op stream under the 1F1B (PipeDream-flush) schedule.

    Returns an ordered list of ``("F", m)`` / ``("B", m)`` ops. Warmup
    runs ``num_stages - stage - 1`` forwards, the steady state strictly
    alternates 1F1B, and the cooldown drains the remaining backwards —
    so at most ``num_stages - stage`` activations are ever live on a
    stage (the memory win over GPipe, at identical bubble).
    """
    if not (0 <= stage < num_stages):
        raise ValueError(f"stage {stage} out of range [0, {num_stages})")
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")
    warmup = min(num_microbatches, num_stages - stage - 1)
    ops: list[tuple[str, int]] = [("F", m) for m in range(warmup)]
    fwd, bwd = warmup, 0
    while fwd < num_microbatches:
        ops.append(("F", fwd))
        fwd += 1
        ops.append(("B", bwd))
        bwd += 1
    while bwd < num_microbatches:
        ops.append(("B", bwd))
        bwd += 1
    return ops


def validate_schedule(
    schedules: Sequence[Sequence[tuple[str, int]]]
) -> None:
    """Check a per-stage op-stream set for pipeline correctness.

    Simulates the stages tick-by-tick with blocking p2p dependencies
    (F(m) at stage s needs F(m) done at s-1; B(m) at stage s needs B(m)
    done at s+1) and raises if any stage's stream would deadlock, skip
    a microbatch, run B(m) before its own F(m), or exceed the 1F1B
    in-flight activation bound of ``num_stages - stage``.
    """
    num_stages = len(schedules)
    done_f = [set() for _ in range(num_stages)]
    done_b = [set() for _ in range(num_stages)]
    cursors = [0] * num_stages
    progressed = True
    while progressed:
        progressed = False
        for s, ops in enumerate(schedules):
            while cursors[s] < len(ops):
                kind, m = ops[cursors[s]]
                if kind == "F":
                    if s > 0 and m not in done_f[s - 1]:
                        break
                    done_f[s].add(m)
                elif kind == "B":
                    if m not in done_f[s]:
                        raise ValueError(
                            f"stage {s}: B({m}) before its own F({m})"
                        )
                    if s < num_stages - 1 and m not in done_b[s + 1]:
                        break
                    done_b[s].add(m)
                else:
                    raise ValueError(f"stage {s}: unknown op {kind!r}")
                live = len(done_f[s]) - len(done_b[s])
                if live > num_stages - s:
                    raise ValueError(
                        f"stage {s}: {live} live activations exceeds the "
                        f"1F1B bound {num_stages - s}"
                    )
                cursors[s] += 1
                progressed = True
    stuck = [s for s in range(num_stages) if cursors[s] < len(schedules[s])]
    if stuck:
        raise ValueError(f"schedule deadlocks at stages {stuck}")
    for s in range(num_stages):
        micro = {m for _, m in schedules[s]}
        if done_f[s] != micro or done_b[s] != micro:
            raise ValueError(f"stage {s}: incomplete F/B coverage")


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """The ideal pipeline-bubble fraction (P-1)/(M+P-1): the share of
    each stage's wall clock spent idle during fill+drain when every
    microbatch tick costs the same. 1F1B and GPipe share this number —
    1F1B only improves the activation-memory bound. The flight recorder
    compares *measured* p2p-wait fractions against it."""
    if num_stages < 1 or num_microbatches < 1:
        raise ValueError("num_stages and num_microbatches must be >= 1")
    return (num_stages - 1) / (num_microbatches + num_stages - 1)


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name, num_micro):
    """Runs inside shard_map. stage_params: this rank's layer shard.
    x_micro: [num_micro, micro_batch, ...] (replicated across pp ranks).
    Returns [num_micro, micro_batch, ...] outputs (replicated)."""
    size = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    shift = [(i, (i + 1) % size) for i in range(size)]

    micro_shape = x_micro.shape[1:]
    outputs = jnp.zeros_like(x_micro)

    def tick(t, carry):
        outputs, buffer = carry
        # Which microbatch does this rank work on at tick t?
        micro_index = t - rank
        active = (micro_index >= 0) & (micro_index < num_micro)
        safe_index = jnp.clip(micro_index, 0, num_micro - 1)
        # Stage 0 reads fresh input; later stages read the hand-off buffer.
        x_in = jnp.where(rank == 0, x_micro[safe_index], buffer)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # Last stage records its finished microbatch.
        record = active & (rank == size - 1)
        outputs = jax.lax.cond(
            record,
            lambda o: o.at[safe_index].set(y),
            lambda o: o,
            outputs,
        )
        # Hand activations to the next stage (ICI neighbor hop).
        buffer = jax.lax.ppermute(y, axis_name, shift)
        return outputs, buffer

    init_buffer = jnp.zeros(micro_shape, x_micro.dtype)
    outputs, _ = jax.lax.fori_loop(
        0, num_micro + size - 1, tick, (outputs, init_buffer)
    )
    # Broadcast final outputs from the last stage to every rank.
    outputs = jax.lax.psum(
        jnp.where(rank == size - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    param_specs=None,
) -> jax.Array:
    """Apply a layer-stacked function as a pipeline.

    stage_fn(stage_params, x) must apply ONE rank's layer shard (e.g. a
    lax.scan over the local layers). stacked_params: pytree whose leaves
    lead with the full layer dim (sharded over `axis_name` here).
    x: [batch, ...] with batch divisible by num_microbatches.
    """
    batch = x.shape[0]
    assert batch % num_microbatches == 0, (batch, num_microbatches)
    micro = batch // num_microbatches
    x_micro = x.reshape(num_microbatches, micro, *x.shape[1:])

    if param_specs is None:
        param_specs = jax.tree.map(
            lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))),
            stacked_params,
        )
    local = functools.partial(
        _pipeline_local,
        stage_fn=stage_fn,
        axis_name=axis_name,
        num_micro=num_microbatches,
    )
    out = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x_micro)
    return out.reshape(batch, *out.shape[2:])


def pipeline_step(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    param_specs=None,
) -> jax.Array:
    """Public entry point: run one pipelined application of ``stage_fn``.

    Single-slice (SPMD) form of the pipeline — stages share one compiled
    program and hand activations over the ``pp`` mesh axis. The MPMD
    cross-slice form lives in train._internal.stage_runner, driven by
    :func:`schedule_1f1b` over the collective p2p plane.
    """
    return pipeline_apply(
        stage_fn,
        stacked_params,
        x,
        mesh=mesh,
        num_microbatches=num_microbatches,
        axis_name=axis_name,
        param_specs=param_specs,
    )

"""Pipeline parallelism — GPipe-style microbatching over the `pp` mesh axis.

The reference expresses pipelines via compiled-graph NCCL channels between
actor stages (python/ray/dag/, SURVEY §2.9 PP row). TPU-native version:
stages live on a `pp` mesh axis; activations hop stage→stage with
`ppermute` inside ONE compiled program (lax.fori_loop over pipeline ticks),
so XLA overlaps the ICI hand-off with each stage's compute.

Layout: layer-stacked params get their leading "layer" dim sharded over pp
(each pp rank holds n_layers / pp_size consecutive layers). The schedule is
the classic (M + P - 1)-tick GPipe fill/drain loop.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name, num_micro):
    """Runs inside shard_map. stage_params: this rank's layer shard.
    x_micro: [num_micro, micro_batch, ...] (replicated across pp ranks).
    Returns [num_micro, micro_batch, ...] outputs (replicated)."""
    size = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    shift = [(i, (i + 1) % size) for i in range(size)]

    micro_shape = x_micro.shape[1:]
    outputs = jnp.zeros_like(x_micro)

    def tick(t, carry):
        outputs, buffer = carry
        # Which microbatch does this rank work on at tick t?
        micro_index = t - rank
        active = (micro_index >= 0) & (micro_index < num_micro)
        safe_index = jnp.clip(micro_index, 0, num_micro - 1)
        # Stage 0 reads fresh input; later stages read the hand-off buffer.
        x_in = jnp.where(rank == 0, x_micro[safe_index], buffer)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # Last stage records its finished microbatch.
        record = active & (rank == size - 1)
        outputs = jax.lax.cond(
            record,
            lambda o: o.at[safe_index].set(y),
            lambda o: o,
            outputs,
        )
        # Hand activations to the next stage (ICI neighbor hop).
        buffer = jax.lax.ppermute(y, axis_name, shift)
        return outputs, buffer

    init_buffer = jnp.zeros(micro_shape, x_micro.dtype)
    outputs, _ = jax.lax.fori_loop(
        0, num_micro + size - 1, tick, (outputs, init_buffer)
    )
    # Broadcast final outputs from the last stage to every rank.
    outputs = jax.lax.psum(
        jnp.where(rank == size - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    param_specs=None,
) -> jax.Array:
    """Apply a layer-stacked function as a pipeline.

    stage_fn(stage_params, x) must apply ONE rank's layer shard (e.g. a
    lax.scan over the local layers). stacked_params: pytree whose leaves
    lead with the full layer dim (sharded over `axis_name` here).
    x: [batch, ...] with batch divisible by num_microbatches.
    """
    batch = x.shape[0]
    assert batch % num_microbatches == 0, (batch, num_microbatches)
    micro = batch // num_microbatches
    x_micro = x.reshape(num_microbatches, micro, *x.shape[1:])

    if param_specs is None:
        param_specs = jax.tree.map(
            lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))),
            stacked_params,
        )
    local = functools.partial(
        _pipeline_local,
        stage_fn=stage_fn,
        axis_name=axis_name,
        num_micro=num_microbatches,
    )
    out = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x_micro)
    return out.reshape(batch, *out.shape[2:])

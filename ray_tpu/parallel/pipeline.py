"""Pipeline parallelism — GPipe-style microbatching over the `pp` mesh axis.

The reference expresses pipelines via compiled-graph NCCL channels between
actor stages (python/ray/dag/, SURVEY §2.9 PP row). TPU-native version:
stages live on a `pp` mesh axis; activations hop stage→stage with
`ppermute` inside ONE compiled program (lax.fori_loop over pipeline ticks),
so XLA overlaps the ICI hand-off with each stage's compute.

Layout: layer-stacked params get their leading "layer" dim sharded over pp
(each pp rank holds n_layers / pp_size consecutive layers). The schedule is
the classic (M + P - 1)-tick GPipe fill/drain loop.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel._compat import shard_map


# ---------------------------------------------------------------------------
# Microbatch scheduling (MPMD stages — the cross-slice pipeline)
# ---------------------------------------------------------------------------
#
# The in-program ppermute pipeline below is the single-slice form. Across
# pod slices the stages are SEPARATE programs on separate gang workers
# (MPMD — "Scaling Deep Learning Training with MPMD Pipeline Parallelism"),
# and the schedule is host-side data each stage runner executes, with p2p
# activation hand-offs providing the cross-stage ordering. The scheduler
# here is pure math (no jax) so the driver, the stage runner, and the
# release gate all share one bubble model.

def schedule_1f1b(
    num_stages: int, num_microbatches: int, stage: int
) -> list[tuple[str, int]]:
    """This stage's op stream under the 1F1B (PipeDream-flush) schedule.

    Returns an ordered list of ``("F", m)`` / ``("B", m)`` ops. Warmup
    runs ``num_stages - stage - 1`` forwards, the steady state strictly
    alternates 1F1B, and the cooldown drains the remaining backwards —
    so at most ``num_stages - stage`` activations are ever live on a
    stage (the memory win over GPipe, at identical bubble).
    """
    if not (0 <= stage < num_stages):
        raise ValueError(f"stage {stage} out of range [0, {num_stages})")
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")
    warmup = min(num_microbatches, num_stages - stage - 1)
    ops: list[tuple[str, int]] = [("F", m) for m in range(warmup)]
    fwd, bwd = warmup, 0
    while fwd < num_microbatches:
        ops.append(("F", fwd))
        fwd += 1
        ops.append(("B", bwd))
        bwd += 1
    while bwd < num_microbatches:
        ops.append(("B", bwd))
        bwd += 1
    return ops


def schedule_interleaved_1f1b(
    num_stages: int,
    num_microbatches: int,
    stage: int,
    num_virtual: int = 1,
) -> list[tuple[str, int, int]]:
    """This RANK's op stream under interleaved 1F1B (Megatron-style
    virtual pipeline stages).

    Each physical rank hosts ``num_virtual`` model CHUNKS; chunk ``c``
    on rank ``r`` is virtual stage ``c * num_stages + r``, so the
    virtual pipeline wraps around the physical ring ``num_virtual``
    times. Microbatches flow through the ranks in groups of
    ``num_stages``: a rank runs ``num_stages`` forwards of chunk 0, then
    the SAME microbatch group through chunk 1, …, and backwards mirror
    in reverse-chunk order. Fill/drain shrinks from one chunk-sized ramp
    to one stage-sized ramp — bubble (S−1)/(M+S−1) → (S−1)/(v·M+S−1),
    see :func:`bubble_fraction`.

    Returns ``("F"|"B", microbatch, chunk)`` ops. ``num_virtual=1``
    reduces exactly to :func:`schedule_1f1b` (with chunk 0 appended).
    ``num_virtual > 1`` requires ``num_microbatches % num_stages == 0``
    (the microbatch-group rotation needs full groups).
    """
    if not (0 <= stage < num_stages):
        raise ValueError(f"stage {stage} out of range [0, {num_stages})")
    if num_microbatches < 1 or num_virtual < 1:
        raise ValueError("num_microbatches and num_virtual must be >= 1")
    if num_virtual == 1:
        return [(kind, m, 0) for kind, m in
                schedule_1f1b(num_stages, num_microbatches, stage)]
    if num_microbatches % num_stages != 0:
        raise ValueError(
            f"interleaved 1F1B needs num_microbatches divisible by "
            f"num_stages, got M={num_microbatches} S={num_stages}"
        )
    total = num_microbatches * num_virtual
    group = num_stages * num_virtual  # one full rotation of the chunks

    def fwd(i: int) -> tuple[str, int, int]:
        chunk = (i // num_stages) % num_virtual
        micro = (i // group) * num_stages + i % num_stages
        return ("F", micro, chunk)

    def bwd(i: int) -> tuple[str, int, int]:
        chunk = num_virtual - 1 - (i // num_stages) % num_virtual
        micro = (i // group) * num_stages + i % num_stages
        return ("B", micro, chunk)

    # Megatron warmup: enough forwards that the LAST virtual stage has
    # run its first microbatch before anyone turns around, plus the
    # 2-per-rank stagger that keeps the steady state collision-free.
    warmup = min(
        total, (num_stages - stage - 1) * 2 + (num_virtual - 1) * num_stages
    )
    ops = [fwd(i) for i in range(warmup)]
    for i in range(total - warmup):
        ops.append(fwd(warmup + i))
        ops.append(bwd(i))
    for i in range(total - warmup, total):
        ops.append(bwd(i))
    return ops


def _normalize_schedules(schedules):
    """Accept both (kind, m) and (kind, m, chunk) op streams."""
    out = []
    for ops in schedules:
        out.append([
            (op[0], op[1], op[2] if len(op) > 2 else 0) for op in ops
        ])
    return out


def validate_schedule(
    schedules: Sequence[Sequence[tuple]],
    num_virtual: int = 1,
) -> None:
    """Check a per-rank op-stream set for pipeline correctness.

    Simulates the ranks tick-by-tick with blocking p2p dependencies and
    raises if any rank's stream would deadlock, skip a microbatch, or
    run B before its own F. Ops may be ``(kind, m)`` (plain 1F1B) or
    ``(kind, m, chunk)`` (interleaved; pass ``num_virtual``). In virtual
    stage terms (vs = chunk·S + rank): F(m) at vs needs F(m) done at
    vs−1, B(m) at vs needs B(m) done at vs+1 — the wraparound hops
    between chunks ride the same physical neighbor links.

    The 1F1B live-activation bound (≤ num_stages − rank) is enforced
    only for ``num_virtual == 1``: interleaving trades that bound for
    the smaller bubble (live activations grow with v by design).
    """
    num_stages = len(schedules)
    schedules = _normalize_schedules(schedules)
    num_vs = num_stages * num_virtual
    done_f: dict[int, set] = {vs: set() for vs in range(num_vs)}
    done_b: dict[int, set] = {vs: set() for vs in range(num_vs)}
    cursors = [0] * num_stages
    progressed = True
    while progressed:
        progressed = False
        for s, ops in enumerate(schedules):
            while cursors[s] < len(ops):
                kind, m, chunk = ops[cursors[s]]
                if not (0 <= chunk < num_virtual):
                    raise ValueError(
                        f"rank {s}: chunk {chunk} out of range "
                        f"[0, {num_virtual})"
                    )
                vs = chunk * num_stages + s
                if kind == "F":
                    if vs > 0 and m not in done_f[vs - 1]:
                        break
                    done_f[vs].add(m)
                elif kind == "B":
                    if m not in done_f[vs]:
                        raise ValueError(
                            f"rank {s}: B({m}) chunk {chunk} before its "
                            f"own F({m})"
                        )
                    if vs < num_vs - 1 and m not in done_b[vs + 1]:
                        break
                    done_b[vs].add(m)
                else:
                    raise ValueError(f"rank {s}: unknown op {kind!r}")
                if num_virtual == 1:
                    live = len(done_f[vs]) - len(done_b[vs])
                    if live > num_stages - s:
                        raise ValueError(
                            f"stage {s}: {live} live activations exceeds "
                            f"the 1F1B bound {num_stages - s}"
                        )
                cursors[s] += 1
                progressed = True
    stuck = [s for s in range(num_stages) if cursors[s] < len(schedules[s])]
    if stuck:
        raise ValueError(f"schedule deadlocks at stages {stuck}")
    for s in range(num_stages):
        for chunk in range(num_virtual):
            vs = chunk * num_stages + s
            micro = {m for kind, m, c in schedules[s] if c == chunk}
            if done_f[vs] != micro or done_b[vs] != micro:
                raise ValueError(
                    f"rank {s} chunk {chunk}: incomplete F/B coverage"
                )


def bubble_fraction(
    num_stages: int, num_microbatches: int, num_virtual: int = 1
) -> float:
    """The ideal pipeline-bubble fraction: the share of each stage's
    wall clock spent idle during fill+drain when every microbatch tick
    costs the same. Plain 1F1B and GPipe share (P−1)/(M+P−1) — 1F1B
    only improves the activation-memory bound. Interleaving the model
    into ``num_virtual`` chunks per rank divides the ramp's share of
    useful work: (P−1)/(v·M+P−1). The flight recorder compares
    *measured* p2p-wait fractions against it."""
    if num_stages < 1 or num_microbatches < 1 or num_virtual < 1:
        raise ValueError(
            "num_stages, num_microbatches, num_virtual must be >= 1"
        )
    return (num_stages - 1) / (
        num_virtual * num_microbatches + num_stages - 1
    )


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name, num_micro):
    """Runs inside shard_map. stage_params: this rank's layer shard.
    x_micro: [num_micro, micro_batch, ...] (replicated across pp ranks).
    Returns [num_micro, micro_batch, ...] outputs (replicated)."""
    size = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    shift = [(i, (i + 1) % size) for i in range(size)]

    micro_shape = x_micro.shape[1:]
    outputs = jnp.zeros_like(x_micro)

    def tick(t, carry):
        outputs, buffer = carry
        # Which microbatch does this rank work on at tick t?
        micro_index = t - rank
        active = (micro_index >= 0) & (micro_index < num_micro)
        safe_index = jnp.clip(micro_index, 0, num_micro - 1)
        # Stage 0 reads fresh input; later stages read the hand-off buffer.
        x_in = jnp.where(rank == 0, x_micro[safe_index], buffer)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # Last stage records its finished microbatch.
        record = active & (rank == size - 1)
        outputs = jax.lax.cond(
            record,
            lambda o: o.at[safe_index].set(y),
            lambda o: o,
            outputs,
        )
        # Hand activations to the next stage (ICI neighbor hop).
        buffer = jax.lax.ppermute(y, axis_name, shift)
        return outputs, buffer

    init_buffer = jnp.zeros(micro_shape, x_micro.dtype)
    outputs, _ = jax.lax.fori_loop(
        0, num_micro + size - 1, tick, (outputs, init_buffer)
    )
    # Broadcast final outputs from the last stage to every rank.
    outputs = jax.lax.psum(
        jnp.where(rank == size - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    param_specs=None,
) -> jax.Array:
    """Apply a layer-stacked function as a pipeline.

    stage_fn(stage_params, x) must apply ONE rank's layer shard (e.g. a
    lax.scan over the local layers). stacked_params: pytree whose leaves
    lead with the full layer dim (sharded over `axis_name` here).
    x: [batch, ...] with batch divisible by num_microbatches.
    """
    batch = x.shape[0]
    assert batch % num_microbatches == 0, (batch, num_microbatches)
    micro = batch // num_microbatches
    x_micro = x.reshape(num_microbatches, micro, *x.shape[1:])

    if param_specs is None:
        param_specs = jax.tree.map(
            lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))),
            stacked_params,
        )
    local = functools.partial(
        _pipeline_local,
        stage_fn=stage_fn,
        axis_name=axis_name,
        num_micro=num_microbatches,
    )
    out = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x_micro)
    return out.reshape(batch, *out.shape[2:])


def pipeline_step(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    param_specs=None,
) -> jax.Array:
    """Public entry point: run one pipelined application of ``stage_fn``.

    Single-slice (SPMD) form of the pipeline — stages share one compiled
    program and hand activations over the ``pp`` mesh axis. The MPMD
    cross-slice form lives in train._internal.stage_runner, driven by
    :func:`schedule_1f1b` over the collective p2p plane.
    """
    return pipeline_apply(
        stage_fn,
        stacked_params,
        x,
        mesh=mesh,
        num_microbatches=num_microbatches,
        axis_name=axis_name,
        param_specs=param_specs,
    )

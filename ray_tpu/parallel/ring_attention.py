"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

The reference has NO native sequence parallelism (SURVEY §5.7: reachable
only by passing DeepSpeed-Ulysses/Megatron-CP configs through Torch shims).
Here it is first-class: the KV shards rotate around the ICI ring via
`ppermute` while each device accumulates blockwise online-softmax attention
for its local queries — neighbor exchange on the TPU torus is near-free, so
the ring overlaps with the attention math.

Both strategies compose with dp/fsdp/tp in one mesh:
  * ring_attention:    KV rotation, O(S_local²·ring) compute per device.
  * ulysses_attention: all_to_all head↔sequence reshard, then full-sequence
    flash locally — cheaper on ICI for attention-heavy shapes (SURVEY §2.9).

Usage: `config.attention = make_ring_attention(mesh)` on the flagship model.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.flash_attention import attention_reference
from ray_tpu.parallel._compat import shard_map

_NEG_INF = -1e30


def _chunk_attention(q, k, v, q_offset, k_offset, causal, scale):
    """Unnormalized blockwise attention of local q against one KV chunk.
    Returns (numerator [B,H,Sq,D], row max m [B,H,Sq,1], row sum l)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        seq_q, seq_k = q.shape[2], k.shape[2]
        q_pos = q_offset + jnp.arange(seq_q)[:, None]
        k_pos = k_offset + jnp.arange(seq_k)[None, :]
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, -1e29)  # fully-masked rows stay finite
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    num = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return num, m, l


def _ring_attention_local(q, k, v, *, axis_name, causal, scale):
    """Runs inside shard_map: q,k,v are the local sequence shards."""
    size = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    seq_local = q.shape[2]
    q_offset = rank * seq_local

    qf = q.astype(jnp.float32)

    def body(step, carry):
        acc, m_run, l_run, k_cur, v_cur = carry
        # The chunk currently held arrived from rank - step (ring rotation).
        src = (rank - step) % size
        num, m_new, l_new = _chunk_attention(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            q_offset, src * seq_local, causal, scale,
        )
        m_tot = jnp.maximum(m_run, m_new)
        alpha = jnp.exp(m_run - m_tot)
        beta = jnp.exp(m_new - m_tot)
        acc = acc * alpha + num * beta
        l_run = l_run * alpha + l_new * beta
        m_run = m_tot
        # Rotate KV to the next neighbor on the ring (ICI hop).
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m_run, l_run, k_next, v_next

    batch, heads, _, dim = q.shape
    init = (
        jnp.zeros((batch, heads, seq_local, dim), jnp.float32),
        jnp.full((batch, heads, seq_local, 1), _NEG_INF, jnp.float32),
        jnp.zeros((batch, heads, seq_local, 1), jnp.float32),
        k, v,
    )
    acc, m_run, l_run, _, _ = jax.lax.fori_loop(0, size, body, init)
    out = acc / jnp.maximum(l_run, 1e-30)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    batch_axes=("dp", "fsdp"),
    head_axis="tp",
    seq_axis="sp",
) -> Callable:
    """Returns attention_fn(q, k, v, causal) for TransformerConfig.attention.
    Arrays are [batch, heads, seq, head_dim]; seq sharded over `sp`."""
    batch_spec = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    head_spec = head_axis if head_axis in mesh.axis_names else None
    spec = P(batch_spec, head_spec, seq_axis, None)

    def attention_fn(q, k, v, causal):
        scale = q.shape[-1] ** -0.5
        local = functools.partial(
            _ring_attention_local, axis_name=seq_axis, causal=causal,
            scale=scale,
        )
        return shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return attention_fn


def _ulysses_local(q, k, v, *, axis_name, causal, scale):
    """all_to_all reshard: seq-sharded [B,H,S/n,D] -> head-sharded
    [B,H/n,S,D], full-sequence attention locally, then reshard back."""
    def to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = attention_reference(qh, kh, vh, causal=causal, scale=scale)
    return to_seq(out.astype(q.dtype))


def make_ulysses_attention(
    mesh: Mesh,
    *,
    batch_axes=("dp", "fsdp"),
    head_axis="tp",
    seq_axis="sp",
) -> Callable:
    """Ulysses-style SP: heads must be divisible by the sp axis size."""
    batch_spec = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    head_spec = head_axis if head_axis in mesh.axis_names else None
    spec = P(batch_spec, head_spec, seq_axis, None)

    def attention_fn(q, k, v, causal):
        scale = q.shape[-1] ** -0.5
        local = functools.partial(
            _ulysses_local, axis_name=seq_axis, causal=causal, scale=scale
        )
        return shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return attention_fn

"""Parallelism primitives — the GSPMD vocabulary of ray_tpu.

One canonical import path for everything the trainer's sharding layer is
built from: mesh construction (:class:`MeshSpec`, :class:`SliceTopology`),
logical-dim sharding rules (:class:`LogicalRules`, :class:`LogicalSpec`,
:func:`auto_shard_specs`), the pipeline schedulers (:func:`pipeline_step`,
:func:`schedule_1f1b`), and the sequence-parallel attention makers.
"""

from ray_tpu.parallel.mesh import (
    AXES,
    DEFAULT_RULES,
    LogicalRules,
    LogicalSpec,
    MeshSpec,
    auto_shard_specs,
    fsdp_extend_spec,
    shard_batch,
    single_host_mesh,
    transformer_tp_rules,
)
from ray_tpu.parallel.pipeline import (
    bubble_fraction,
    pipeline_apply,
    pipeline_step,
    schedule_1f1b,
    schedule_interleaved_1f1b,
    validate_schedule,
)
from ray_tpu.parallel.ring_attention import (
    make_ring_attention,
    make_ulysses_attention,
)
from ray_tpu.parallel.topology import SliceTopology

__all__ = [
    "AXES",
    "DEFAULT_RULES",
    "LogicalRules",
    "LogicalSpec",
    "MeshSpec",
    "SliceTopology",
    "auto_shard_specs",
    "bubble_fraction",
    "fsdp_extend_spec",
    "make_ring_attention",
    "make_ulysses_attention",
    "pipeline_apply",
    "pipeline_step",
    "schedule_1f1b",
    "schedule_interleaved_1f1b",
    "shard_batch",
    "single_host_mesh",
    "transformer_tp_rules",
    "validate_schedule",
]

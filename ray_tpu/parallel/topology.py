"""Multi-slice topology — meshes that span ICI domains over DCN.

Role-equivalent of the reference's multi-node process-group layout
(its NCCL world spanning hosts) re-designed for TPU multi-slice
(SURVEY §2.9 multi-slice row, §5.8): a pod slice is one ICI domain;
training across several slices rides the data-center network (DCN).
The mesh must encode that boundary — collective-heavy axes (tp/sp/...)
stay INSIDE a slice, cheap axes (dp gradient sync) cross slices — or
XLA will happily route a tensor-parallel all-reduce over DCN.

``SliceTopology`` builds exactly that mesh from a jax runtime whose
processes span slices (jax.distributed): DCN axes outermost, ICI axes
innermost, device order arranged [slice, in-slice] so any collective
over an ICI axis touches one slice only. On the CPU twin
(xla_force_host_platform_device_count per process), a process plays
the role of a slice — the same code path the driver's dryrun and the
2-process tests exercise.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import numpy as np


def _group_by_domain(devices: Sequence[Any]) -> dict[int, list]:
    """Group devices by ICI domain. Real multi-slice TPU runtimes expose
    a distinguishing slice_index (several host processes share one
    slice); when slice_index is absent or constant (CPU twin reports 0
    on every device; single slice), the owning process is the domain."""
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    use_slice = len(slice_ids) > 1 and None not in slice_ids
    groups: dict[int, list] = {}
    for d in devices:
        key = int(d.slice_index) if use_slice else int(d.process_index)
        groups.setdefault(key, []).append(d)
    return groups


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Axis layout for a multi-slice mesh.

    ici_axes — named axes laid out WITHIN a slice (tp/sp/fsdp...).
    dcn_axes — named axes laid out ACROSS slices (usually {"dp": n}).

    prod(dcn_axes) must equal the number of slices; prod(ici_axes) the
    devices per slice.
    """

    ici_axes: Mapping[str, int]
    dcn_axes: Mapping[str, int]

    def __post_init__(self):
        overlap = set(self.ici_axes) & set(self.dcn_axes)
        if overlap:
            raise ValueError(f"axes on both tiers: {sorted(overlap)}")
        if not self.ici_axes or not self.dcn_axes:
            raise ValueError("both ici_axes and dcn_axes must be non-empty")

    @property
    def num_slices(self) -> int:
        return math.prod(self.dcn_axes.values())

    @property
    def devices_per_slice(self) -> int:
        return math.prod(self.ici_axes.values())

    def axis_names(self) -> tuple[str, ...]:
        return (*self.dcn_axes.keys(), *self.ici_axes.keys())

    def build_mesh(self, devices: Sequence[Any] | None = None):
        """Mesh with DCN axes outermost over slice-grouped devices."""
        import jax
        from jax.sharding import Mesh

        devices = list(devices if devices is not None else jax.devices())
        groups = _group_by_domain(devices)
        if len(groups) != self.num_slices:
            raise ValueError(
                f"topology wants {self.num_slices} slices "
                f"(prod of dcn_axes), runtime has {len(groups)} "
                f"ICI domains"
            )
        per = self.devices_per_slice
        rows = []
        for key in sorted(groups):
            members = sorted(groups[key], key=lambda d: d.id)
            if len(members) != per:
                raise ValueError(
                    f"slice {key} has {len(members)} devices, topology "
                    f"wants {per} (prod of ici_axes)"
                )
            rows.append(members)
        grid = np.array(rows, dtype=object).reshape(
            *self.dcn_axes.values(), *self.ici_axes.values()
        )
        return Mesh(grid, self.axis_names())

    # -- hierarchical collectives ---------------------------------------
    def hierarchical_psum(self, x, *, ici: bool = True, dcn: bool = True):
        """psum placed tier by tier (use inside shard_map over this
        topology's mesh): reduce within the slice first (ICI), then
        across slices (DCN) — the two-tier gradient sync. Axis order
        makes the communication placement explicit instead of leaving
        one flat psum's decomposition to the compiler."""
        import jax

        if ici:
            for name in self.ici_axes:
                x = jax.lax.psum(x, name)
        if dcn:
            for name in self.dcn_axes:
                x = jax.lax.psum(x, name)
        return x

    def hierarchical_pmean(self, x, *, ici: bool = True, dcn: bool = True):
        """Tier-ordered mean: :meth:`hierarchical_psum` divided by the
        number of participants actually reduced over — the drop-in
        gradient-averaging form for data-parallel sync."""
        total = self.hierarchical_psum(x, ici=ici, dcn=dcn)
        participants = 1
        if ici:
            participants *= self.devices_per_slice
        if dcn:
            participants *= self.num_slices
        return total / participants

    def grad_sync_axes(self) -> tuple[str, ...]:
        """The DCN axes a data-parallel gradient sync reduces over."""
        return tuple(self.dcn_axes.keys())

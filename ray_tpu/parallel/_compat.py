"""jax version compatibility for the parallel package.

``jax.shard_map`` (with the ``check_vma`` kwarg) is the modern spelling;
older jax only ships ``jax.experimental.shard_map.shard_map`` (kwarg
``check_rep``). One import site so every pipeline/attention module works
on both without scattering try/excepts.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma kwarg
    from jax import shard_map as shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
            **kw,
        )


__all__ = ["shard_map"]

"""ray_tpu — a TPU-native distributed computing framework.

Tasks, actors, and a distributed object store (the Ray-equivalent core),
plus `xla`-backend collectives over ICI, mesh-axis parallelism
(DP/FSDP/TP/PP/SP/EP), and ML libraries: train, tune, data, serve, rllib —
all designed TPU-first on JAX/XLA/Pallas.

Public API parity target: python/ray/__init__.py of the reference
(ray.init/remote/get/put/wait/kill, actors, placement groups, ...).
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private import worker as _worker
from ray_tpu._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    timeline,
    wait,
)
from ray_tpu.actor import ActorClass, ActorHandle, get_actor
from ray_tpu.remote_function import RemoteFunction
from ray_tpu import exceptions

__version__ = "0.1.0"

_DEFAULT_OPTION_KEYS = {
    "num_cpus", "num_tpus", "num_returns", "resources", "max_retries",
    "retry_exceptions", "runtime_env", "scheduling_strategy", "name",
    "namespace", "lifetime", "max_restarts", "max_task_retries",
    "max_concurrency", "memory",
}


def remote(*args, **options):
    """@ray_tpu.remote — turn a function into a task or a class into an actor.

    Usage (same shapes as the reference's @ray.remote):
        @ray_tpu.remote
        def f(x): ...

        @ray_tpu.remote(num_cpus=2, num_tpus=1)
        class A: ...
    """
    if len(args) == 1 and not options and (callable(args[0]) or isinstance(args[0], type)):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only")
    bad = set(options) - _DEFAULT_OPTION_KEYS
    if bad:
        raise TypeError(f"unknown @remote options: {sorted(bad)}")

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, **{
                k: v for k, v in options.items()
                if k not in ("num_returns", "max_retries", "retry_exceptions", "memory")
            })
        return RemoteFunction(target, **{
            k: v for k, v in options.items()
            if k in ("num_returns", "num_cpus", "num_tpus", "resources",
                     "max_retries", "retry_exceptions", "runtime_env",
                     "scheduling_strategy")
        })

    return decorator


def get_runtime_context() -> dict:
    ctx = _worker.get_global_context()
    return {
        "job_id": ctx.job_id,
        "node_id": ctx.node_id,
        "worker_id": ctx.worker_id,
        "is_driver": ctx.is_driver,
    }


__all__ = [
    "ObjectRef",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "cancel",
    "kill",
    "nodes",
    "timeline",
    "cluster_resources",
    "available_resources",
    "get_actor",
    "get_runtime_context",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "exceptions",
]

"""User-facing exceptions.

Role-equivalent of python/ray/exceptions.py in the reference
(RayError/RayTaskError/ActorDiedError/ObjectLostError/...).
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised; carries the remote traceback.

    Reference: RayTaskError — raised from ray.get() at the caller, so the
    remote failure surfaces at the point the value is consumed.
    """

    def __init__(self, task_name: str, remote_traceback: str):
        self.task_name = task_name
        self.remote_traceback = remote_traceback
        super().__init__(
            f"task {task_name!r} failed remotely:\n{remote_traceback}"
        )

    def __reduce__(self):
        return (TaskError, (self.task_name, self.remote_traceback))


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel() (reference:
    ray.exceptions.TaskCancelledError, python/ray/tests/test_cancel.py)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died (e.g. OOM-killed, segfault)."""


class OutOfMemoryError(WorkerCrashedError):
    """The node's memory monitor killed the worker (reference
    memory_monitor.cc / raylet OOM-killer role, N15): a system failure
    distinct from application exceptions — it participates in task
    retries (max_retries) and never masquerades as user code raising."""


class ActorDiedError(RayTpuError):
    """The actor is permanently dead (restarts exhausted or never restartable)."""


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class DAGActorDiedError(ActorDiedError):
    """An actor participating in a compiled DAG died while an execution
    was in flight. Raised from DAGRef.get() instead of a bare timeout so
    callers can distinguish 'the graph is dead' from 'the graph is
    slow'; names the dead actor and its device-plane rank so the report
    lines up with the hang doctor's suspect ranks, plus the edge it was
    detected on — channel name, family, channel epoch, and the seq
    frontier the consumer was blocked at — so the DAG supervisor and the
    hang report agree on the blast radius."""

    def __init__(self, dag_id: str, actor_id: str, rank: int,
                 detail: str = "", *, channel: str | None = None,
                 family: str | None = None, epoch: int | None = None,
                 seq: int | None = None):
        self.dag_id = dag_id
        self.actor_id = actor_id
        self.rank = rank
        self.detail = detail
        self.channel = channel
        self.family = family
        self.epoch = epoch
        self.seq = seq
        message = (
            f"compiled DAG {dag_id}: actor {actor_id} (dag rank {rank}) "
            "died with executions in flight"
        )
        if channel is not None:
            message += (
                f" [detected on {family or '?'} channel {channel}"
                f" epoch={epoch} seq frontier={seq}]"
            )
        if detail:
            message += f": {detail}"
        super().__init__(message)

    def __reduce__(self):
        # 3rd element updates __dict__ on unpickle, so the edge evidence
        # survives the wire without breaking older (dag_id, actor_id,
        # rank) consumers.
        return (
            DAGActorDiedError,
            (self.dag_id, self.actor_id, self.rank, self.detail),
            {
                "channel": self.channel, "family": self.family,
                "epoch": self.epoch, "seq": self.seq,
            },
        )


class ReplicaDiedError(RayTpuError):
    """The serve replica backing an in-flight request died mid-call and
    the request could not be completed on another replica. Raised by
    DeploymentResponse.result() instead of a bare timeout/actor error so
    callers can distinguish 'my request is lost' from 'my request is
    slow' (the handle already spent its RetryPolicy budget against
    healthy replicas)."""

    def __init__(self, deployment: str, replica: str, detail: str = ""):
        self.deployment = deployment
        self.replica = replica
        message = (
            f"replica {replica!r} of deployment {deployment!r} died "
            f"while serving the request"
        )
        if detail:
            message += f": {detail}"
        super().__init__(message)

    def __reduce__(self):
        return (ReplicaDiedError, (self.deployment, self.replica))


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's propagated serve Deadline expired before completion.

    Subclasses TimeoutError so callers that handled the old bare
    GetTimeoutError-style timeouts keep working; distinct from it so SLO
    accounting can tell 'the budget ran out' from 'an internal get timed
    out'. Maps to HTTP 504 at the proxy."""

    def __init__(self, detail: str = ""):
        self.detail = detail
        super().__init__(detail or "request deadline exceeded")

    def __reduce__(self):
        return (DeadlineExceededError, (self.detail,))


class RequestShedError(RayTpuError):
    """Admission control rejected the request before doing work (queue depth
    projected past the route SLO). Maps to HTTP 503 + Retry-After at the
    proxy; never retried by the handle — retrying amplifies overload."""

    def __init__(self, detail: str = "", retry_after_s: float = 1.0):
        self.detail = detail
        self.retry_after_s = retry_after_s
        super().__init__(detail or "request shed by admission control")

    def __reduce__(self):
        return (RequestShedError, (self.detail, self.retry_after_s))


class ReplicaDrainingError(RayTpuError):
    """The replica is draining (oom_risk / SIGTERM / scale-down) and not
    accepting new work. The handle retries another replica without charging
    the circuit breaker — draining is deliberate, not a fault."""

    def __init__(self, replica: str = ""):
        self.replica = replica
        super().__init__(
            f"replica {replica!r} is draining and not accepting requests"
        )

    def __reduce__(self):
        return (ReplicaDrainingError, (self.replica,))


class ObjectLostError(RayTpuError):
    """All copies of the object are gone and it could not be reconstructed."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get(..., timeout=) expired."""


class ObjectStoreFullError(RayTpuError):
    """The shared-memory store cannot fit the object even after eviction."""


class RuntimeEnvSetupError(RayTpuError):
    """Worker runtime environment failed to materialize."""


class PlacementGroupUnschedulableError(RayTpuError):
    """The placement group cannot fit on the cluster."""


class GangDiedError(RayTpuError):
    """A member of an SPMD worker gang died; the gang's collectives are wedged
    and the whole gang is the failure domain (see SURVEY §5.3)."""

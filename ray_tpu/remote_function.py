"""@ray_tpu.remote functions.

Role-equivalent of python/ray/remote_function.py :: RemoteFunction._remote:
options handling (num_cpus/resources/num_returns/max_retries/runtime_env/
scheduling_strategy) and pickled-function export through the controller KV
function table (the reference exports via GCS KV the same way).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any

from ray_tpu._private import serialization, worker


class RemoteFunction:
    def __init__(self, fn, **default_options):
        self._fn = fn
        self._options = {
            "num_returns": 1,
            "num_cpus": 1,
            "resources": None,
            "max_retries": None,
            "retry_exceptions": False,
            "runtime_env": None,
            "scheduling_strategy": None,
        }
        self._options.update(default_options)
        self._function_id: str | None = None
        self._exported_for: str | None = None  # job id of the exporting cluster
        self._export_lock = threading.Lock()
        # (ctx, template) — static spec fields cached per cluster context
        self._submit_cache: tuple | None = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function cannot be called directly; use "
            f"{self.__name__}.remote(...)"
        )

    def options(self, **options) -> "RemoteFunction":
        clone = RemoteFunction(self._fn, **{**self._options, **options})
        clone._function_id = self._function_id
        clone._exported_for = self._exported_for
        return clone

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_export_lock", None)
        state.pop("_submit_cache", None)  # holds a live CoreContext
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._export_lock = threading.Lock()
        self._submit_cache = None
        if "_exported_for" not in self.__dict__:
            self._exported_for = None

    def _ensure_exported(self) -> str:
        # The export is per-CLUSTER: a module-level @remote function
        # outlives ray_tpu.shutdown()/init() cycles (test modules, repeated
        # drivers), and the next cluster's controller KV starts empty. A
        # plain "already exported" boolean made workers' function-table
        # lookups miss forever on the second cluster.
        ctx = worker.get_global_context()
        cluster_key = ctx.job_id
        if self._function_id is not None and self._exported_for == cluster_key:
            return self._function_id
        with self._export_lock:
            if self._function_id is None or self._exported_for != cluster_key:
                raw = serialization.dumps_function(self._fn)
                function_id = "fn-" + hashlib.sha1(raw).hexdigest()[:20]
                ctx.io.run(
                    ctx.controller.call(
                        "kv_put",
                        {
                            "namespace": "funcs",
                            "key": function_id,
                            "value": raw,
                            "overwrite": False,
                        },
                    )
                )
                self._function_id = function_id
                self._exported_for = cluster_key
        return self._function_id

    def remote(self, *args, **kwargs):
        ctx = worker.get_global_context()
        function_id = self._ensure_exported()
        opts = self._options
        cache = self._submit_cache
        if cache is None or cache[0] is not ctx:
            resources = dict(opts["resources"] or {})
            if opts["num_cpus"] is not None:
                resources.setdefault("CPU", opts["num_cpus"])
            num_tpus = opts.get("num_tpus")
            if num_tpus:
                resources["TPU"] = num_tpus
            template = ctx.make_spec_template(
                function_id=function_id,
                name=self.__name__,
                num_returns=opts["num_returns"],
                resources=resources,
                max_retries=opts["max_retries"],
                retry_exceptions=opts["retry_exceptions"],
                runtime_env=opts["runtime_env"],
                scheduling_strategy=opts["scheduling_strategy"],
            )
            self._submit_cache = cache = (ctx, template)
        refs = ctx.submit_task(
            args=args, kwargs=kwargs, spec_template=cache[1],
        )
        return refs[0] if opts["num_returns"] == 1 else refs

"""CLI — `python -m ray_tpu <command>`.

Role-equivalent of python/ray/scripts/scripts.py (`ray start/stop/status/
list/summary/timeline/microbenchmark`) + the job CLI (SURVEY §2.2 L7).
`start --head` keeps a cluster alive in the foreground and prints the
address for `init(address=...)` / RAYTPU_ADDRESS.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _connect(args) -> None:
    import ray_tpu

    address = getattr(args, "address", None)
    ray_tpu.init(address=address or "auto")


def cmd_start(args) -> None:
    import ray_tpu

    if not args.head:
        print("only --head is supported in-process; worker nodes join via "
              "cluster_utils or the autoscaler", file=sys.stderr)
        sys.exit(2)
    resources = json.loads(args.resources) if args.resources else {}
    autoscaling = None
    if args.autoscaler:
        autoscaling = {
            "version": args.autoscaler,
            "provider": args.provider,
            "idle_timeout_s": args.autoscaler_idle_timeout,
        }
    ray_tpu.init(
        num_cpus=args.num_cpus, resources=resources, autoscaling=autoscaling
    )
    if autoscaling:
        print(f"autoscaler {args.autoscaler} ({args.provider}) monitoring")
    from ray_tpu._private import worker as worker_mod

    controller = worker_mod.get_global_context().controller_addr
    address = f"{controller[0]}:{controller[1]}"
    print(f"ray_tpu head started. Connect with:\n"
          f"  RAYTPU_ADDRESS={address}\n"
          f"  ray_tpu.init(address=\"{address}\")")
    if args.dashboard:
        from ray_tpu.dashboard import start_dashboard

        start_dashboard(port=args.dashboard_port)
        print(f"dashboard at http://127.0.0.1:{args.dashboard_port}")
    if args.block:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass


def cmd_status(args) -> None:
    _connect(args)
    import ray_tpu

    print(json.dumps(
        {
            "cluster_resources": ray_tpu.cluster_resources(),
            "available_resources": ray_tpu.available_resources(),
            "nodes": len(ray_tpu.nodes()),
        },
        indent=2,
    ))


def cmd_list(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    fn = {
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "tasks": state.list_tasks,
        "workers": state.list_workers,
        "placement-groups": state.list_placement_groups,
        "jobs": state.list_jobs,
    }[args.kind]
    print(json.dumps(fn(limit=args.limit), indent=2, default=str))


def cmd_summary(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    fn = {"tasks": state.summarize_tasks, "actors": state.summarize_actors}[
        args.kind
    ]
    print(json.dumps(fn(), indent=2))


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if value < 1024 or unit == "TiB":
            return f"{value:.1f}{unit}" if unit != "B" else f"{int(value)}B"
        value /= 1024
    return f"{value:.1f}TiB"


def _render_top(summary: dict, comm: dict | None = None) -> str:
    """One refresh frame of `ray_tpu top`: per-node utilization lines +
    the heaviest workers by RSS, from the controller's telemetry store,
    plus the comm-plane flight line (in-flight ops / stalls) when the
    comm summary is available."""
    comm = comm or {}
    inflight_total = sum(
        int(v.get("inflight", 0)) for v in (comm.get("inflight") or {}).values()
    )
    last_age = comm.get("last_stall_age_s")
    comm_bits = (
        f"  comm_inflight={inflight_total}"
        f"  comm_stalls={comm.get('stall_total', 0)}"
        + (f"  last_stall={last_age:.0f}s ago" if last_age is not None else "")
    )
    lines = [
        time.strftime("%H:%M:%S")
        + f"  nodes={len(summary.get('nodes') or {})}"
        + f"  samples={summary.get('total_ingested', 0)}"
        + f"  dropped={summary.get('total_dropped', 0)}"
        + f"  oom_risk={summary.get('oom_risk_events', 0)}"
        + comm_bits,
        "",
        f"{'NODE':<14}{'CPU%':>6}{'MEM':>18}{'WORKERS':>9}"
        f"{'RSS(total)':>12}{'OBJSTORE':>10}{'HBM':>16}  TIERS",
    ]
    workers: list[tuple[int, str, str]] = []
    for node_id, entry in sorted((summary.get("nodes") or {}).items()):
        latest = entry.get("latest") or {}
        points = entry.get("points") or {}
        hbm = (
            f"{_fmt_bytes(latest.get('hbm_used'))}/"
            f"{_fmt_bytes(latest.get('hbm_total'))}"
            if latest.get("hbm_total")
            else "-"
        )
        mem = (
            f"{_fmt_bytes(latest.get('mem_used'))}/"
            f"{_fmt_bytes(latest.get('mem_total'))}"
        )
        tiers = (
            f"raw:{points.get('raw', 0)} 10s:{points.get('10s', 0)} "
            f"60s:{points.get('60s', 0)}"
        )
        alive = "" if entry.get("alive", True) else " (dead)"
        lines.append(
            f"{node_id[-12:]:<14}"
            f"{latest.get('cpu_percent', 0):>6.1f}"
            f"{mem:>18}"
            f"{latest.get('num_workers', 0):>9}"
            f"{_fmt_bytes(latest.get('workers_rss_total')):>12}"
            f"{_fmt_bytes(latest.get('object_store_bytes')):>10}"
            f"{hbm:>16}  {tiers}{alive}"
        )
        for worker_id, rss in (latest.get("worker_rss") or {}).items():
            workers.append((int(rss), worker_id, node_id))
    workers.sort(reverse=True)
    if workers:
        comm_by_worker = comm.get("inflight") or {}
        lines += [
            "",
            f"{'WORKER':<28}{'NODE':<14}{'RSS':>12}"
            f"{'COMM_INFL':>11}{'OLDEST':>9}",
        ]
        for rss, worker_id, node_id in workers[:15]:
            slot = comm_by_worker.get(worker_id) or {}
            infl = int(slot.get("inflight", 0))
            oldest = slot.get("oldest_age_s", 0.0) or 0.0
            lines.append(
                f"{worker_id[-26:]:<28}{node_id[-12:]:<14}"
                f"{_fmt_bytes(rss):>12}"
                f"{infl:>11}"
                f"{(f'{oldest:.1f}s' if infl else '-'):>9}"
            )
    return "\n".join(lines)


def cmd_top(args) -> None:
    """Live cluster utilization (`htop` role): refreshes per-node CPU /
    memory / worker-RSS / object-store / HBM from the telemetry store."""
    _connect(args)
    from ray_tpu.util import state

    if args.json:
        # One machine-readable shot (ISSUE 8 satellite): the raw
        # summaries scripts would otherwise scrape from the rendered
        # frame.
        print(json.dumps(
            {
                "resources": state.summarize_resources(),
                "workload": state.summarize_workload(),
                "goodput": state.summarize_goodput(),
                "commflight": state.summarize_commflight(),
            },
            indent=2, default=str,
        ))
        return
    while True:
        frame = _render_top(
            state.summarize_resources(), state.summarize_commflight()
        )
        if args.once:
            print(frame)
            return
        # ANSI clear + home keeps the display in place like top(1).
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return


def cmd_diagnose(args) -> None:
    """`ray_tpu diagnose` — ranked findings over every observability
    surface (ISSUE 8): training phase balance (data/comm/checkpoint
    bound), stragglers cross-referenced with node telemetry, elastic-run
    goodput, serve SLOs, and node hot spots."""
    _connect(args)
    from ray_tpu._private import workload as workload_mod
    from ray_tpu.util import state

    snapshot = state.collect_diagnose_snapshot()
    findings = workload_mod.diagnose(snapshot)
    if args.json:
        print(json.dumps({"findings": findings}, indent=2, default=str))
        return
    tags = {"crit": "CRIT", "warn": "WARN", "info": "info"}
    print(f"ray_tpu diagnose — {len(findings)} finding(s)")
    for f in findings:
        print(f"  [{tags.get(f['severity'], '????'):<4}] {f['message']}")


def cmd_doctor(args) -> None:
    """`ray_tpu doctor --hang` — the cluster-wide hang report: which
    ranks are missing from which (group, tag, seq), who the waiters'
    wire records point at, protocol drift vs the static commgraph, and
    (with --stacks) every wedged rank's native stack."""
    _connect(args)
    from ray_tpu.util import state

    report = state.get_hang_report(
        fresh=args.fresh, stacks=args.stacks or args.json
    )
    if args.json:
        print(json.dumps(report, indent=2, default=str))
        return
    channels = report.get("channels") or []
    stalls = report.get("stall_events") or []
    print(
        f"ray_tpu doctor — {len(stalls)} stall event(s), "
        f"{len(channels)} stalled channel(s), "
        f"{report.get('workers_reporting', 0)} worker(s) reporting"
    )
    for line in report.get("summary") or []:
        print(f"  {line}")
    for c in channels:
        print(f"\n  channel {c['channel']} (frontier seq {c['frontier_seq']}, "
              f"world {c['world_size']})")
        for w in c.get("waiting_ranks", []):
            peer = f" <- rank {w['peer']}" if w.get("peer", -1) >= 0 else ""
            print(f"    waiting: rank {w['rank']} seq {w['seq']} "
                  f"age {w['age_s']:.1f}s{peer}"
                  + (f" [{w['site']}]" if w.get("site") else ""))
        if c.get("missing_ranks"):
            print(f"    MISSING: rank(s) "
                  f"{', '.join(map(str, c['missing_ranks']))} — no record "
                  "at the frontier")
        if c.get("protocol_drift"):
            print("    PROTOCOL DRIFT: channel absent from the certified "
                  "static commgraph (rtgraph)")
    if args.stacks:
        for wid, blob in (report.get("stacks") or {}).items():
            print(f"\n== {wid} (pid {blob.get('pid')}, "
                  f"task {blob.get('current_task')}) ==")
            for label, text in (blob.get("stacks") or {}).items():
                print(f"-- {label} --\n{text}")
    if not channels and not stalls:
        print("  no comm stalls suspected — the comm plane looks healthy")


def cmd_stacks(args) -> None:
    """`ray_tpu stacks` — native Python stacks of every worker on every
    alive node (the dashboard Stack Trace button, cluster-wide)."""
    _connect(args)
    from ray_tpu.util import state

    nodes = state.collect_cluster_stacks()
    if args.json:
        print(json.dumps(nodes, indent=2, default=str))
        return
    for node_id, res in sorted(nodes.items()):
        if res.get("status") != "ok":
            print(f"== node {node_id}: {res.get('error', 'unreachable')} ==")
            continue
        for wid, wres in sorted((res.get("workers") or {}).items()):
            if wres.get("status") != "ok":
                print(f"== {node_id} / {wid}: "
                      f"{wres.get('error', 'unreachable')} ==")
                continue
            print(f"== {node_id} / {wid} (pid {wres.get('pid')}, "
                  f"task {wres.get('current_task')}) ==")
            for label, text in (wres.get("stacks") or {}).items():
                print(f"-- {label} --\n{text}")


def cmd_timeline(args) -> None:
    if getattr(args, "seq", None):
        # Single-sequence view (ISSUE 19): every span sharing the
        # sequence's trace id + one instant per emitted token. Reads
        # session files directly — works offline against a finished
        # session via RAYTPU_SESSION_DIR, no cluster connection needed.
        from ray_tpu.util import state as state_mod
        from ray_tpu.util.timeline import build_sequence_trace

        session_dir = state_mod._session_dir()
        if not session_dir:
            _connect(args)
            session_dir = state_mod._session_dir()
        if not session_dir:
            raise SystemExit("timeline --seq: no session directory "
                             "(set RAYTPU_SESSION_DIR or run inside a "
                             "cluster)")
        try:
            trace = build_sequence_trace(session_dir, args.seq)
        except KeyError as exc:
            raise SystemExit(str(exc))
    else:
        _connect(args)
        import ray_tpu

        trace = ray_tpu.timeline()
    out = args.out or args.output
    from ray_tpu._private.atomic_io import atomic_write_json

    atomic_write_json(out, trace)
    n = len(trace.get("traceEvents", []))
    print(f"wrote {n} events to {out} (load in ui.perfetto.dev "
          f"or chrome://tracing)")


def cmd_profile(args) -> None:
    """`ray_tpu profile --steps N [--ranks 0,3]` — coordinated
    step-aligned capture (ISSUE 20): every selected rank arms at the
    same upcoming step boundary, captures N steps of device trace +
    host samples, and the controller merges everything into ONE
    Perfetto trace joined to the run's trace ids."""
    _connect(args)
    from ray_tpu.util import state

    ranks = None
    if args.ranks:
        try:
            ranks = [int(r) for r in args.ranks.split(",") if r.strip()]
        except ValueError:
            raise SystemExit(f"--ranks must be comma-separated ints, "
                             f"got {args.ranks!r}")
    rec = state.capture_profile(
        steps=args.steps, ranks=ranks, timeout_s=args.timeout,
    )
    if args.out and rec.get("path"):
        import shutil

        try:
            shutil.copyfile(rec["path"], args.out)
            rec = dict(rec, copied_to=args.out)
        except OSError as exc:
            rec = dict(rec, copy_error=str(exc))
    if args.json:
        print(json.dumps(rec, indent=2, default=str))
        return
    status = rec.get("status", "error")
    if status in ("ok", "partial"):
        print(f"capture {rec.get('capture_id')}: {status} — "
              f"{len(rec.get('ranks') or [])} rank(s), "
              f"steps {rec.get('start_step')}+{rec.get('steps')}")
        if rec.get("path"):
            print(f"  merged trace : {rec['path']} "
                  "(load in ui.perfetto.dev)")
        if rec.get("folded_path"):
            print(f"  folded stacks: {rec['folded_path']}")
        if rec.get("copied_to"):
            print(f"  copied to    : {rec['copied_to']}")
        for rank, hot in sorted((rec.get("hot_phases") or {}).items(),
                                key=lambda kv: str(kv[0])):
            if isinstance(hot, dict) and hot.get("phase"):
                print(f"  rank {rank}: hot phase '{hot['phase']}' "
                      f"({float(hot.get('frac') or 0.0):.0%})")
    else:
        raise SystemExit(
            f"capture failed: {rec.get('code') or rec.get('error') or rec}"
        )


def cmd_microbenchmark(args) -> None:
    from ray_tpu._private.ray_perf import main as perf_main

    perf_main()


def cmd_serve(args) -> None:
    """`ray_tpu serve {deploy,status,shutdown}` (reference: serve CLI)."""
    _connect(args)
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        deployed = serve.run_from_config(args.config)
        print(json.dumps({"deployed": deployed}))
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(getattr(args, "address", None))
    if args.job_cmd == "submit":
        job_id = client.submit_job(entrypoint=args.entrypoint)
        print(job_id)
        if args.wait:
            status = client.wait_until_finished(job_id)
            print(status)
            print(client.get_job_logs(job_id))
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.job_cmd == "stop":
        print(client.stop_job(args.job_id))
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--resources", default="")
    p.add_argument("--block", action="store_true")
    p.add_argument("--dashboard", action="store_true")
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument(
        "--autoscaler", choices=["v1", "v2"], default=None,
        help="launch the autoscaler monitor with the head",
    )
    p.add_argument(
        "--provider", default="podslice",
        help="autoscaler node provider (default: podslice)",
    )
    p.add_argument("--autoscaler-idle-timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list")
    p.add_argument(
        "kind",
        choices=["actors", "nodes", "tasks", "workers", "placement-groups", "jobs"],
    )
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary")
    p.add_argument("kind", choices=["tasks", "actors"])
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("top", help="live cluster resource utilization")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (no screen clearing)")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable snapshot "
                        "(resources + workload + goodput) and exit")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "diagnose",
        help="ranked findings: phase balance, stragglers, goodput, "
             "serve SLOs",
    )
    p.add_argument("--json", action="store_true")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_diagnose)

    p = sub.add_parser(
        "doctor",
        help="cluster-wide hang report: which ranks are missing from "
             "which (group, tag, seq) comm channel",
    )
    p.add_argument("--hang", action="store_true",
                   help="diagnose a suspected comm hang (the default and "
                        "only mode today)")
    p.add_argument("--fresh", action="store_true",
                   help="force a cluster-wide evidence harvest now "
                        "instead of returning the last report")
    p.add_argument("--stacks", action="store_true",
                   help="include every rank's native stack dump")
    p.add_argument("--json", action="store_true")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "stacks",
        help="native Python stacks of every worker on every alive node",
    )
    p.add_argument("--json", action="store_true")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_stacks)

    p = sub.add_parser("timeline")
    p.add_argument("--output", default="timeline.json")
    p.add_argument("--out", default=None,
                   help="alias for --output (ray_tpu timeline --out trace.json)")
    p.add_argument("--seq", default=None,
                   help="request id: export ONE served sequence's trace "
                        "(spans sharing its trace id + per-token instants)")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "profile",
        help="coordinated step-aligned profile capture across the gang "
             "(merged Perfetto trace + folded host stacks)",
    )
    p.add_argument("--steps", type=int, default=3,
                   help="number of training steps to capture (default 3)")
    p.add_argument("--ranks", default=None,
                   help="comma-separated world ranks (default: all)")
    p.add_argument("--out", default=None,
                   help="also copy the merged trace to this path")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="seconds to wait for the capture (default 300)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("microbenchmark")
    p.set_defaults(fn=cmd_microbenchmark)

    p = sub.add_parser(
        "lint",
        help="framework-aware static analysis (distributed-hazard rules "
             "+ lockset deadlock checks); see docs/devtools.md",
    )
    from ray_tpu.devtools.lint.runner import add_lint_arguments, cmd_lint

    add_lint_arguments(p)
    p.set_defaults(fn=lambda a: sys.exit(cmd_lint(a)))

    p = sub.add_parser("job")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("entrypoint")
    js.add_argument("--wait", action="store_true")
    js.add_argument("--address", default=None)
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("job_id")
        jp.add_argument("--address", default=None)
    jl = jsub.add_parser("list")
    jl.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("serve", help="deploy/inspect serve applications")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    sd = ssub.add_parser("deploy", help="apply a YAML deploy config")
    sd.add_argument("config")
    sd.add_argument("--address", default=None)
    for name in ("status", "shutdown"):
        sp = ssub.add_parser(name)
        sp.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_serve)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()

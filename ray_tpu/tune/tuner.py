"""Tuner — the experiment entry point.

Role-equivalent of python/ray/tune/tuner.py :: Tuner (+ impl/tuner_internal
and tune.py :: run). Accepts a function trainable, a Trainable subclass, or
a ray_tpu.train trainer instance (which is wrapped so param_space's
`train_loop_config` merges into the trainer — the reference's
Tuner(trainer) path, SURVEY §3.3).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu.train.config import RunConfig
from ray_tpu.tune.execution.tune_controller import TuneController
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers.trial_scheduler import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher
from ray_tpu.tune.trainable import Trainable, report, wrap_function


@dataclass
class TuneConfig:
    """Mirrors ray.tune.TuneConfig."""

    metric: str | None = None
    mode: str | None = None
    search_alg: Searcher | None = None
    scheduler: TrialScheduler | None = None
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    time_budget_s: float | None = None
    reuse_actors: bool = False
    seed: int | None = None


def _is_trainer(obj: Any) -> bool:
    return hasattr(obj, "fit") and hasattr(obj, "train_loop_config")


def _wrap_trainer(trainer) -> Callable[[dict], None]:
    """Run a copy of the trainer inside the trial, forwarding per-round
    metrics to tune.report via a RunConfig callback."""

    def trainer_trainable(config: dict):
        import copy

        local = copy.copy(trainer)
        local.train_loop_config = {
            **trainer.train_loop_config,
            **config.get("train_loop_config", {}),
        }
        for key, value in config.items():
            if key != "train_loop_config" and hasattr(local, key):
                setattr(local, key, value)

        class _Forward:
            def on_result(self, metrics: dict) -> None:
                report(metrics)

        local.run_config = copy.copy(local.run_config or RunConfig())
        local.run_config.callbacks = list(local.run_config.callbacks) + [_Forward()]
        result = local.fit()
        if result.error is not None:
            raise result.error

    trainer_trainable.__name__ = type(trainer).__name__
    return trainer_trainable


class Tuner:
    def __init__(
        self,
        trainable: Any,
        *,
        param_space: dict | None = None,
        tune_config: TuneConfig | None = None,
        run_config: RunConfig | None = None,
        _restore_path: str | None = None,
        _resume_errored: bool = False,
    ):
        self.param_space = dict(param_space or {})
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_path = _restore_path
        self._resume_errored = _resume_errored

        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self._trainable_cls = trainable
            self._name = trainable.__name__
        elif _is_trainer(trainable):
            fn = _wrap_trainer(trainable)
            self._trainable_cls = wrap_function(fn)
            self._name = fn.__name__
        elif callable(trainable):
            self._trainable_cls = wrap_function(trainable)
            self._name = getattr(trainable, "__name__", "trainable")
        else:
            raise TypeError(f"cannot tune {trainable!r}")

    @classmethod
    def restore(
        cls,
        path: str,
        trainable: Any,
        *,
        param_space: dict | None = None,
        resume_errored: bool = False,
    ) -> "Tuner":
        """Rebuild a Tuner from an experiment dir written by a prior fit()."""
        run_config = RunConfig(
            name=os.path.basename(path.rstrip("/")),
            storage_path=os.path.dirname(path.rstrip("/")),
        )
        return cls(
            trainable,
            param_space=param_space,
            run_config=run_config,
            _restore_path=path,
            _resume_errored=resume_errored,
        )

    @classmethod
    def can_restore(cls, path: str) -> bool:
        return os.path.exists(os.path.join(path, "experiment_state.json"))

    def _experiment_dir(self) -> str:
        name = self.run_config.name or f"{self._name}_experiment"
        return os.path.join(self.run_config.resolved_storage_path(), name)

    def fit(self) -> ResultGrid:
        from ray_tpu._private import usage

        usage.record_feature("tune")
        cfg = self.tune_config
        searcher = cfg.search_alg or BasicVariantGenerator(
            self.param_space,
            num_samples=cfg.num_samples,
            random_state=cfg.seed,
        )
        if cfg.max_concurrent_trials and not isinstance(
            searcher, ConcurrencyLimiter
        ):
            searcher = ConcurrencyLimiter(searcher, cfg.max_concurrent_trials)
        searcher.set_search_properties(cfg.metric, cfg.mode, self.param_space)

        num_samples_cap = None
        if isinstance(searcher, BasicVariantGenerator):
            num_samples_cap = searcher.total_samples
        elif cfg.num_samples > 0:
            num_samples_cap = cfg.num_samples

        controller = TuneController(
            self._trainable_cls,
            searcher=searcher,
            scheduler=cfg.scheduler or FIFOScheduler(),
            metric=cfg.metric,
            mode=cfg.mode,
            num_samples_cap=num_samples_cap,
            max_concurrent_trials=cfg.max_concurrent_trials,
            experiment_dir=self._experiment_dir(),
            stopping_criteria=dict(self.run_config.stop or {}),
            max_failures=self.run_config.failure_config.max_failures,
            checkpoint_freq=self.run_config.checkpoint_config.checkpoint_frequency,
            callbacks=self.run_config.callbacks,
            time_budget_s=cfg.time_budget_s,
        )
        if self._restore_path:
            controller.restore_experiment_state(self._resume_errored)
        trials = controller.run()
        return ResultGrid(trials, cfg.metric, cfg.mode)


def run(
    trainable: Any,
    *,
    config: dict | None = None,
    metric: str | None = None,
    mode: str | None = None,
    num_samples: int = 1,
    scheduler: TrialScheduler | None = None,
    search_alg: Searcher | None = None,
    stop: dict | None = None,
    storage_path: str | None = None,
    name: str | None = None,
    max_concurrent_trials: int | None = None,
    time_budget_s: float | None = None,
) -> ResultGrid:
    """ray.tune.run-equivalent convenience wrapper over Tuner."""
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            scheduler=scheduler,
            search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
            time_budget_s=time_budget_s,
        ),
        run_config=RunConfig(name=name, storage_path=storage_path, stop=stop),
    )
    return tuner.fit()

"""Trial — one hyperparameter configuration's lifecycle.

Role-equivalent of python/ray/tune/experiment/trial.py :: Trial. FSM:
PENDING → RUNNING ⇄ PAUSED → TERMINATED | ERROR. The controller owns all
transitions; this object is pure state (serializable for experiment resume).
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Any, Optional

from ray_tpu._private import atomic_io

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

_VALID = {
    PENDING: {RUNNING, TERMINATED, ERROR},
    RUNNING: {PAUSED, TERMINATED, ERROR, PENDING},
    PAUSED: {RUNNING, TERMINATED, ERROR},
    TERMINATED: set(),
    ERROR: {PENDING},  # retry resets to PENDING
}


class Trial:
    def __init__(
        self,
        trainable_name: str,
        config: dict,
        trial_id: str | None = None,
        experiment_dir: str = "",
        stopping_criteria: dict | None = None,
        max_failures: int = 0,
    ):
        self.trainable_name = trainable_name
        self.config = config
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.experiment_dir = experiment_dir
        self.stopping_criteria = dict(stopping_criteria or {})
        self.max_failures = max_failures

        self.status = PENDING
        self.last_result: dict = {}
        self.metric_history: list[dict] = []
        self.num_failures = 0
        self.error_message: str | None = None
        # Latest checkpoint as an opaque blob ref/path (controller-managed).
        self.checkpoint: Any = None
        self.checkpoint_iter: int = 0
        self.iteration = 0

    @property
    def local_dir(self) -> str:
        d = os.path.join(self.experiment_dir, f"{self.trainable_name}_{self.trial_id}")
        os.makedirs(d, exist_ok=True)
        return d

    def set_status(self, status: str) -> None:
        if status != self.status and status not in _VALID[self.status]:
            raise ValueError(f"invalid transition {self.status} → {status}")
        self.status = status

    def should_stop(self, result: dict) -> bool:
        return any(
            key in result and result[key] >= bound
            for key, bound in self.stopping_criteria.items()
        )

    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    # -- experiment-state (resume) serialization --

    def to_json(self) -> dict:
        return {
            "trainable_name": self.trainable_name,
            "config": self.config,
            "trial_id": self.trial_id,
            "status": TERMINATED if self.status == RUNNING else self.status,
            "last_result": self.last_result,
            "num_failures": self.num_failures,
            "error_message": self.error_message,
            "iteration": self.iteration,
            "checkpoint_iter": self.checkpoint_iter,
            "stopping_criteria": self.stopping_criteria,
            "max_failures": self.max_failures,
        }

    @classmethod
    def from_json(cls, data: dict, experiment_dir: str) -> "Trial":
        trial = cls(
            data["trainable_name"],
            data["config"],
            trial_id=data["trial_id"],
            experiment_dir=experiment_dir,
            stopping_criteria=data.get("stopping_criteria"),
            max_failures=data.get("max_failures", 0),
        )
        trial.status = data["status"]
        trial.last_result = data["last_result"]
        trial.num_failures = data["num_failures"]
        trial.error_message = data.get("error_message")
        trial.iteration = data.get("iteration", 0)
        trial.checkpoint_iter = data.get("checkpoint_iter", 0)
        ckpt_file = os.path.join(trial.local_dir, "checkpoint.json")
        if os.path.exists(ckpt_file):
            with open(ckpt_file) as f:
                trial.checkpoint = json.load(f).get("data")
        return trial

    def persist_checkpoint(self) -> None:
        """Durable copy for Tuner.restore (PBT exploits stay in-memory)."""
        if self.checkpoint is None:
            return
        try:
            atomic_io.atomic_write_json(
                os.path.join(self.local_dir, "checkpoint.json"),
                {"data": self.checkpoint, "iter": self.checkpoint_iter},
            )
        except TypeError:  # rtlint: disable=swallowed-exception - non-json-serializable checkpoint: resume restarts fresh, by design
            pass

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, iter={self.iteration})"

"""Per-trial metric sinks.

Role-equivalent of python/ray/tune/logger/{csv,json,tensorboardx}.py —
callbacks the controller fires on every trial event. TensorBoard support
writes tfevents via a minimal record writer only if tensorboardX is
importable; CSV/JSONL always work.
"""

from __future__ import annotations

import csv
import json
import os
import time
from typing import IO


class LoggerCallback:
    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: dict) -> None:
        pass

    def on_trial_complete(self, trial, result: dict) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass


class JsonLoggerCallback(LoggerCallback):
    """result.json — one JSON line per reported result (reference format)."""

    def __init__(self):
        self._files: dict[str, IO] = {}

    def on_trial_result(self, trial, result: dict) -> None:
        f = self._files.get(trial.trial_id)
        if f is None:
            f = open(os.path.join(trial.local_dir, "result.json"), "a")
            self._files[trial.trial_id] = f
        payload = {k: v for k, v in result.items() if _jsonable(v)}
        payload["timestamp"] = time.time()
        f.write(json.dumps(payload) + "\n")
        f.flush()

    def on_trial_complete(self, trial, result: dict) -> None:
        f = self._files.pop(trial.trial_id, None)
        if f:
            f.close()


class CSVLoggerCallback(LoggerCallback):
    """progress.csv — header from the first result's keys."""

    def __init__(self):
        self._writers: dict[str, tuple[IO, csv.DictWriter]] = {}

    def on_trial_result(self, trial, result: dict) -> None:
        flat = {k: v for k, v in result.items() if _scalar(v)}
        entry = self._writers.get(trial.trial_id)
        if entry is None:
            f = open(os.path.join(trial.local_dir, "progress.csv"), "a", newline="")
            writer = csv.DictWriter(f, fieldnames=sorted(flat))
            writer.writeheader()
            self._writers[trial.trial_id] = (f, writer)
        else:
            f, writer = entry
        self._writers[trial.trial_id][1].writerow(
            {k: flat.get(k, "") for k in self._writers[trial.trial_id][1].fieldnames}
        )
        f.flush()

    def on_trial_complete(self, trial, result: dict) -> None:
        entry = self._writers.pop(trial.trial_id, None)
        if entry:
            entry[0].close()


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard scalars (reference: logger/tensorboardx.py). Prefers
    tensorboardX; falls back to torch.utils.tensorboard (present in this
    image), so real tfevents files are written without extra deps."""

    def __init__(self):
        self._writer_cls = None
        self._dir_kw = "logdir"
        try:
            from tensorboardX import SummaryWriter  # noqa: F401

            self._writer_cls = SummaryWriter
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._writer_cls = SummaryWriter
                self._dir_kw = "log_dir"
            except ImportError:
                pass
        self._writers: dict[str, object] = {}

    def on_trial_result(self, trial, result: dict) -> None:
        if self._writer_cls is None:
            return
        writer = self._writers.get(trial.trial_id)
        if writer is None:
            writer = self._writer_cls(**{self._dir_kw: trial.local_dir})
            self._writers[trial.trial_id] = writer
        step = result.get("training_iteration", 0)
        for key, value in result.items():
            if _scalar(value) and not isinstance(value, (str, bool)):
                writer.add_scalar(f"ray_tpu/tune/{key}", value, step)

    def on_trial_complete(self, trial, result: dict) -> None:
        writer = self._writers.pop(trial.trial_id, None)
        if writer is not None:
            writer.close()


def _scalar(value) -> bool:
    return isinstance(value, (int, float, str, bool))


def _jsonable(value) -> bool:
    try:
        json.dumps(value)
        return True
    except (TypeError, ValueError):
        return False

"""TPESearch — in-tree Tree-structured Parzen Estimator searcher.

Role-equivalent of the reference's HyperOpt adapter
(python/ray/tune/search/hyperopt/hyperopt_search.py), reimplemented
dependency-free: the classic TPE recipe (Bergstra et al. 2011) over the
ray_tpu.tune.search.sample domains.

Per suggest(): completed trials split into "good" (top gamma quantile by
the objective) and "bad"; each dimension builds a Parzen density l(x)
from the good observations (Gaussian mixture for numerics in the
domain's — possibly log — metric space; smoothed counts for
categoricals) and g(x) from the bad ones; n_candidates samples drawn
from l are scored by l(x)/g(x) and the argmax wins. Until
n_initial_points trials complete, suggestions are random (the warmup
that seeds the densities).
"""

from __future__ import annotations

import math
import random
from typing import Any, Optional

from ray_tpu.tune.search.sample import (
    Categorical, Domain, Float, Function, Integer, Quantized,
)
from ray_tpu.tune.search.searcher import Searcher


def _to_metric_space(domain, value: float) -> float:
    if getattr(domain, "log", False):
        return math.log(value)
    return float(value)


def _from_metric_space(domain, value: float):
    if getattr(domain, "log", False):
        value = math.exp(value)
    if isinstance(domain, Integer):
        return int(min(domain.upper - 1, max(domain.lower, round(value))))
    return float(min(domain.upper, max(domain.lower, value)))


class _NumericParzen:
    """1-D Gaussian mixture over observations in metric space."""

    def __init__(self, domain, observations: list[float]):
        self.domain = domain
        lo = _to_metric_space(domain, domain.lower)
        hi = _to_metric_space(domain, domain.upper)
        self.lo, self.hi = lo, hi
        self.points = [_to_metric_space(domain, v) for v in observations]
        span = max(hi - lo, 1e-12)
        # Silverman-flavored bandwidth, floored so densities never spike
        n = max(len(self.points), 1)
        self.bw = max(span / (n ** 0.5 + 1), span * 0.02)

    def sample(self, rng: random.Random) -> float:
        if not self.points:
            return rng.uniform(self.lo, self.hi)
        center = rng.choice(self.points)
        for _ in range(16):
            draw = rng.gauss(center, self.bw)
            if self.lo <= draw <= self.hi:
                return draw
        return min(self.hi, max(self.lo, draw))

    def pdf(self, x: float) -> float:
        if not self.points:
            return 1.0 / max(self.hi - self.lo, 1e-12)
        total = 0.0
        inv = 1.0 / (self.bw * math.sqrt(2 * math.pi))
        for center in self.points:
            z = (x - center) / self.bw
            total += inv * math.exp(-0.5 * z * z)
        return total / len(self.points) + 1e-12


class _CategoricalParzen:
    def __init__(self, domain: Categorical, observations: list):
        self.domain = domain
        self.counts = {id(c): 1.0 for c in domain.categories}  # +1 smooth
        self._by_id = {id(c): c for c in domain.categories}
        for obs in observations:
            for cat in domain.categories:
                if obs == cat:
                    self.counts[id(cat)] += 1.0
                    break
        self.total = sum(self.counts.values())

    def sample(self, rng: random.Random):
        r = rng.uniform(0, self.total)
        acc = 0.0
        for key, weight in self.counts.items():
            acc += weight
            if r <= acc:
                return self._by_id[key]
        return self.domain.categories[-1]

    def pdf(self, value) -> float:
        for cat in self.domain.categories:
            if value == cat:
                return self.counts[id(cat)] / self.total
        return 1e-12


class TPESearch(Searcher):
    def __init__(
        self,
        space: dict | None = None,
        metric: str | None = None,
        mode: str | None = None,
        n_initial_points: int = 10,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: int | None = None,
    ):
        super().__init__(metric, mode)
        self._space = dict(space or {})
        self.n_initial_points = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._live: dict[str, dict] = {}
        self._observations: list[tuple[dict, float]] = []

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = {
                k: v for k, v in config.items() if isinstance(v, Domain)
            }
        return True

    # -- the TPE step ---------------------------------------------------
    def _split(self) -> tuple[list[dict], list[dict]]:
        ranked = sorted(  # best first
            self._observations,
            key=lambda o: (-o[1] if self.mode == "max" else o[1]),
        )
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        good = [cfg for cfg, _ in ranked[:n_good]]
        bad = [cfg for cfg, _ in ranked[n_good:]] or good
        return good, bad

    def _suggest_dimension(self, key: str, domain) -> Any:
        base = domain.inner if isinstance(domain, Quantized) else domain
        good, bad = self._split()
        good_obs = [cfg[key] for cfg in good if key in cfg]
        bad_obs = [cfg[key] for cfg in bad if key in cfg]
        if isinstance(base, Categorical):
            l_density = _CategoricalParzen(base, good_obs)
            g_density = _CategoricalParzen(base, bad_obs)
            candidates = [
                l_density.sample(self._rng) for _ in range(self.n_candidates)
            ]
            best = max(
                candidates,
                key=lambda c: l_density.pdf(c) / g_density.pdf(c),
            )
            return best
        if isinstance(base, (Float, Integer)):
            l_density = _NumericParzen(base, good_obs)
            g_density = _NumericParzen(base, bad_obs)
            draws = [
                l_density.sample(self._rng) for _ in range(self.n_candidates)
            ]
            best = max(
                draws, key=lambda x: l_density.pdf(x) / g_density.pdf(x)
            )
            value = _from_metric_space(base, best)
            if isinstance(domain, Quantized):
                value = round(round(value / domain.q) * domain.q, 10)
            return value
        return domain.sample(self._rng)  # Function and friends: random

    def suggest(self, trial_id: str) -> Optional[dict]:
        if not self._space:
            return None
        config: dict = {}
        warmup = len(self._observations) < self.n_initial_points
        for key, domain in self._space.items():
            if not isinstance(domain, Domain) or isinstance(domain, Function):
                config[key] = (
                    domain.sample(self._rng, config)
                    if isinstance(domain, Function)
                    else domain
                )
            elif warmup:
                config[key] = domain.sample(self._rng)
            else:
                config[key] = self._suggest_dimension(key, domain)
        self._live[trial_id] = config
        return config

    def on_trial_complete(
        self, trial_id: str, result: dict | None = None, error: bool = False
    ) -> None:
        config = self._live.pop(trial_id, None)
        if config is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        self._observations.append((config, float(value)))

    def save(self) -> Any:
        return {
            "observations": self._observations,
            "live": dict(self._live),
            "rng": self._rng.getstate(),
        }

    def restore(self, state: Any) -> None:
        self._observations = list(state["observations"])
        self._live = dict(state.get("live", {}))
        self._rng.setstate(state["rng"])

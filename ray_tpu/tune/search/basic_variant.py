"""Grid + random search.

Role-equivalent of python/ray/tune/search/basic_variant.py ::
BasicVariantGenerator. Resolves a param_space into concrete trial configs:
grid_search axes expand as a cross product, Domain leaves sample from a
seeded RNG, and the whole expansion repeats `num_samples` times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Iterator, Optional

from ray_tpu.tune.search.sample import Domain, Function, _GridSearch
from ray_tpu.tune.search.searcher import Searcher


def _is_grid(value: Any) -> bool:
    return (
        isinstance(value, _GridSearch)
        or (isinstance(value, dict) and set(value) == {"grid_search"})
    )


def _grid_values(value: Any) -> list:
    return value.values if isinstance(value, _GridSearch) else value["grid_search"]


def _walk(space: dict, path=()) -> Iterator[tuple[tuple, Any]]:
    for key, value in space.items():
        here = path + (key,)
        if isinstance(value, dict) and not _is_grid(value):
            yield from _walk(value, here)
        else:
            yield here, value


def _set_path(config: dict, path: tuple, value: Any) -> None:
    node = config
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


class _Spec:
    """`spec.config` view handed to sample_from lambdas."""

    def __init__(self, config: dict):
        self.config = config


def generate_variants(
    space: dict, rng: random.Random
) -> Iterator[dict]:
    """One full expansion of the space: cross product of grids × one sample
    of every Domain leaf. sample_from leaves resolve last, seeing the
    partially-resolved config."""
    leaves = list(_walk(space))
    grid_axes = [(p, _grid_values(v)) for p, v in leaves if _is_grid(v)]
    grid_paths = [p for p, _ in grid_axes]
    for combo in itertools.product(*[vals for _, vals in grid_axes]) if grid_axes else [()]:
        config: dict = {}
        for path, value in zip(grid_paths, combo):
            _set_path(config, path, value)
        deferred: list[tuple[tuple, Function]] = []
        for path, value in leaves:
            if path in grid_paths:
                continue
            if isinstance(value, Function):
                deferred.append((path, value))
            elif isinstance(value, Domain):
                _set_path(config, path, value.sample(rng))
            else:
                _set_path(config, path, value)
        for path, fn in deferred:
            _set_path(config, path, fn.sample(rng, _Spec(config)))
        yield config


class BasicVariantGenerator(Searcher):
    def __init__(
        self,
        space: dict | None = None,
        num_samples: int = 1,
        random_state: int | None = None,
        points_to_evaluate: list[dict] | None = None,
        max_concurrent: int = 0,
    ):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._seed = random_state
        self._rng = random.Random(random_state)
        self._points = list(points_to_evaluate or [])
        self.max_concurrent = max_concurrent
        self._iterator: Optional[Iterator[dict]] = None
        self._emitted = 0

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = config
            self._iterator = None
        return True

    @property
    def total_samples(self) -> int:
        grid = 1
        for _, value in _walk(self._space):
            if _is_grid(value):
                grid *= len(_grid_values(value))
        return grid * self._num_samples + len(self._points)

    def _variants(self) -> Iterator[dict]:
        for point in self._points:
            yield dict(point)
        for _ in range(self._num_samples):
            yield from generate_variants(self._space, self._rng)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._iterator is None:
            self._iterator = self._variants()
        try:
            config = next(self._iterator)
        except StopIteration:
            return None
        self._emitted += 1
        return config

    def save(self):
        # Replaying `emitted` suggestions against the same seed reproduces
        # RNG state, so resume only needs the counter.
        return {"emitted": self._emitted, "seed": self._seed}

    def restore(self, state):
        self._rng = random.Random(state["seed"])
        self._iterator = self._variants()
        for _ in range(state["emitted"]):
            next(self._iterator, None)
        self._emitted = state["emitted"]

"""OptunaSearch — adapter to the Optuna TPE sampler.

Role-equivalent of python/ray/tune/search/optuna/optuna_search.py ::
OptunaSearch. Gated on `import optuna` (not baked into this image); the
adapter maps ray_tpu.tune.search.sample Domains onto an optuna
distribution per suggest() call, and feeds completed results back as
optuna trials — same translation the reference performs.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer, Quantized
from ray_tpu.tune.search.searcher import Searcher

try:
    import optuna as _optuna
except ImportError:  # pragma: no cover - optional dependency
    _optuna = None


class OptunaSearch(Searcher):
    def __init__(
        self,
        space: dict | None = None,
        metric: str | None = None,
        mode: str | None = None,
        sampler=None,
        seed: int | None = None,
    ):
        if _optuna is None:
            raise ImportError(
                "OptunaSearch requires `optuna`, which is not installed. "
                "Use BasicVariantGenerator or ASHAScheduler instead."
            )
        super().__init__(metric, mode)
        self._space = space or {}
        self._sampler = sampler or _optuna.samplers.TPESampler(seed=seed)
        self._study = _optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=self._sampler,
        )
        self._ot_trials: dict[str, object] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = config
        return True

    def _suggest_param(self, ot_trial, name: str, domain) -> object:
        if isinstance(domain, Quantized):
            inner = domain.inner
            if isinstance(inner, Float):
                return ot_trial.suggest_float(
                    name, inner.lower, inner.upper, step=domain.q, log=inner.log
                )
        if isinstance(domain, Float):
            return ot_trial.suggest_float(
                name, domain.lower, domain.upper, log=domain.log
            )
        if isinstance(domain, Integer):
            return ot_trial.suggest_int(
                name, domain.lower, domain.upper - 1, log=domain.log
            )
        if isinstance(domain, Categorical):
            return ot_trial.suggest_categorical(name, domain.categories)
        return domain

    def suggest(self, trial_id: str) -> Optional[dict]:
        ot_trial = self._study.ask()
        self._ot_trials[trial_id] = ot_trial
        config = {}
        for name, domain in self._space.items():
            if isinstance(domain, Domain):
                config[name] = self._suggest_param(ot_trial, name, domain)
            else:
                config[name] = domain
        return config

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        ot_trial = self._ot_trials.pop(trial_id, None)
        if ot_trial is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot_trial, state=_optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot_trial, result[self.metric])

"""Search-space primitives.

Role-equivalent of python/ray/tune/search/sample.py :: Domain / Float /
Integer / Categorical / Function and python/ray/tune/search/variant_generator
grid_search marker. Domains are declarative samplers; the variant generator
resolves them against a seeded RNG so experiments are reproducible and
resumable.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Sequence


class Domain:
    """A sampleable hyperparameter dimension."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if lower >= upper:
            raise ValueError("lower must be < upper")
        if log and lower <= 0:
            raise ValueError("loguniform needs lower > 0")
        self.lower, self.upper, self.log = float(lower), float(upper), log

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math

            return math.exp(
                rng.uniform(math.log(self.lower), math.log(self.upper))
            )
        return rng.uniform(self.lower, self.upper)

    def quantized(self, q: float) -> "Quantized":
        return Quantized(self, q)

    def __repr__(self):
        kind = "loguniform" if self.log else "uniform"
        return f"{kind}({self.lower}, {self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        if lower >= upper:
            raise ValueError("lower must be < upper")
        self.lower, self.upper, self.log = int(lower), int(upper), log

    def sample(self, rng: random.Random) -> int:
        if self.log:
            import math

            return int(
                math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
            )
        return rng.randrange(self.lower, self.upper)

    def __repr__(self):
        return f"randint({self.lower}, {self.upper})"


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        if not categories:
            raise ValueError("choice() needs at least one option")
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)

    def __repr__(self):
        return f"choice({self.categories!r})"


class Function(Domain):
    """sample_from(lambda spec: ...) — spec exposes resolved config so far."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def sample(self, rng: random.Random, spec: Any = None) -> Any:
        try:
            return self.fn(spec)
        except TypeError:
            return self.fn()


class Quantized(Domain):
    def __init__(self, inner: Domain, q: float):
        self.inner, self.q = inner, q

    def sample(self, rng: random.Random) -> float:
        value = self.inner.sample(rng)
        return round(round(value / self.q) * self.q, 10)


class _GridSearch:
    """Marker resolved by BasicVariantGenerator into a cross-product axis."""

    def __init__(self, values: Sequence[Any]):
        if not values:
            raise ValueError("grid_search needs at least one value")
        self.values = list(values)

    def __repr__(self):
        return f"grid_search({self.values!r})"


# -- public constructors (same names as ray.tune.*) --

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Quantized:
    return Float(lower, upper).quantized(q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[Any], Any]) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> dict:
    # The reference encodes grid_search as {"grid_search": [...]} dict; keep
    # that wire shape so user configs round-trip through json.
    return {"grid_search": list(values)}


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda _=None: random.gauss(mean, sd))

"""Searcher protocol + ConcurrencyLimiter.

Role-equivalent of python/ray/tune/search/searcher.py :: Searcher and
python/ray/tune/search/concurrency_limiter.py :: ConcurrencyLimiter.
A Searcher proposes configs (`suggest`) and learns from completed trials
(`on_trial_complete`); external HPO libs adapt through this interface.
"""

from __future__ import annotations

from typing import Any, Optional


class Searcher:
    def __init__(self, metric: str | None = None, mode: str | None = None):
        if mode not in (None, "min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode

    def set_search_properties(
        self, metric: str | None, mode: str | None, config: dict
    ) -> bool:
        """Late-bind metric/mode/space from TuneConfig. True if accepted."""
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[dict]:
        """Next config, or None when the space is exhausted / must wait."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(
        self, trial_id: str, result: dict | None = None, error: bool = False
    ) -> None:
        pass

    def save(self) -> Any:
        return None

    def restore(self, state: Any) -> None:
        pass


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from the wrapped searcher."""

    def __init__(self, searcher: Searcher, max_concurrent: int, batch: bool = False):
        super().__init__(searcher.metric, searcher.mode)
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self.batch = batch
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        ok = self.searcher.set_search_properties(metric, mode, config)
        self.metric, self.mode = self.searcher.metric, self.searcher.mode
        return ok

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._live) >= self.max_concurrent:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def save(self):
        return {"live": sorted(self._live), "inner": self.searcher.save()}

    def restore(self, state):
        self._live = set(state["live"])
        self.searcher.restore(state["inner"])

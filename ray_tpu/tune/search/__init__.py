from ray_tpu.tune.search.basic_variant import BasicVariantGenerator, generate_variants
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher
from ray_tpu.tune.search.tpe import TPESearch

__all__ = [
    "Searcher",
    "ConcurrencyLimiter",
    "BasicVariantGenerator",
    "generate_variants",
    "TPESearch",
]

"""TuneController — THE trial event loop.

Role-equivalent of python/ray/tune/execution/tune_controller.py ::
TuneController (SURVEY §2.5, §3.3): asks the searcher for configs, launches
trial actors, consumes intermediate results, consults the scheduler
(CONTINUE/PAUSE/STOP), persists experiment state for resume, restarts failed
trials from their last checkpoint, and supports PBT checkpoint transplants.

Trials execute as ray_tpu actors (one per trial). Checkpoints move between
controller and trial actors as picklable blobs through the object store —
PBT exploits are actor-to-actor via the controller, the same economics as
the reference's checkpoint-dir copies.
"""

from __future__ import annotations

import json
import logging
import os
import time
import traceback
from typing import Any, Optional

import ray_tpu
from ray_tpu._private import atomic_io
from ray_tpu.tune.experiment.trial import (
    ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial,
)
from ray_tpu.tune.schedulers.trial_scheduler import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import Trainable

EXPERIMENT_STATE_FILE = "experiment_state.json"

logger = logging.getLogger(__name__)


@ray_tpu.remote
class _TrialActor:
    """Hosts one Trainable instance; remote surface mirrors the reference's
    trainable-actor protocol (train/save/restore/reset/stop)."""

    def __init__(self, trainable_cls: type, config: dict):
        self._trainable: Trainable = trainable_cls(config)

    def train(self) -> dict:
        return self._trainable.train()

    def save(self) -> Any:
        return self._trainable.save()

    def restore(self, checkpoint: Any) -> str:
        self._trainable.restore(checkpoint)
        return "ok"

    def reset(self, new_config: dict) -> bool:
        return self._trainable.reset(new_config)

    def stop(self) -> str:
        self._trainable.stop()
        return "ok"


class TuneController:
    def __init__(
        self,
        trainable_cls: type,
        *,
        searcher: Searcher,
        scheduler: TrialScheduler | None = None,
        metric: str | None = None,
        mode: str | None = None,
        num_samples_cap: int | None = None,
        max_concurrent_trials: int | None = None,
        experiment_dir: str = "",
        stopping_criteria: dict | None = None,
        max_failures: int = 0,
        checkpoint_freq: int = 0,
        resources_per_trial: dict | None = None,
        callbacks: list | None = None,
        time_budget_s: float | None = None,
    ):
        self.trainable_cls = trainable_cls
        self.trainable_name = getattr(trainable_cls, "__name__", "trainable")
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.metric, self.mode = metric, mode
        self.scheduler.set_search_properties(metric, mode)
        self.searcher.set_search_properties(metric, mode, {})
        self.num_samples_cap = num_samples_cap
        self.experiment_dir = experiment_dir
        os.makedirs(experiment_dir, exist_ok=True)
        self.stopping_criteria = dict(stopping_criteria or {})
        self.max_failures = max_failures
        self.checkpoint_freq = checkpoint_freq
        self.resources_per_trial = dict(resources_per_trial or {"CPU": 1})
        self.callbacks = list(callbacks or [])
        self.time_budget_s = time_budget_s

        self.trials: list[Trial] = []
        self._actors: dict[str, Any] = {}  # trial_id -> ActorHandle
        self._futures: dict[Any, Trial] = {}  # train() ObjectRef -> Trial
        self._searcher_exhausted = False
        if max_concurrent_trials:
            self._max_concurrent = max_concurrent_trials
        else:
            try:
                cpus = ray_tpu.cluster_resources().get("CPU", 4)
            except Exception:
                cpus = 4
            per_trial = max(self.resources_per_trial.get("CPU", 1), 0.01)
            self._max_concurrent = max(1, int(cpus / per_trial))

    # -- scheduler hooks --

    @property
    def live_trials(self) -> list[Trial]:
        return [t for t in self.trials if not t.is_finished()]

    def transplant_trial(self, trial: Trial, donor: Trial, new_config: dict) -> None:
        """PBT exploit: copy donor's latest checkpoint + new config into
        trial's actor (reset in place or recreate)."""
        donor_actor = self._actors.get(donor.trial_id)
        if donor_actor is not None:
            try:
                donor.checkpoint = ray_tpu.get(donor_actor.save.remote(), timeout=60)
                donor.checkpoint_iter = donor.iteration
            except Exception:
                # Exploit proceeds from the donor's LAST saved checkpoint.
                logger.warning(
                    "transplant: saving donor %s failed; using its last "
                    "checkpoint (iter %s)",
                    donor.trial_id, donor.checkpoint_iter, exc_info=True,
                )
        trial.config = dict(new_config)
        trial.checkpoint = donor.checkpoint
        trial.checkpoint_iter = donor.checkpoint_iter
        actor = self._actors.get(trial.trial_id)
        if actor is None:
            return
        try:
            in_place = ray_tpu.get(actor.reset.remote(new_config), timeout=60)
        except Exception:
            in_place = False
        if not in_place:
            self._drop_pending_future(trial)
            self._kill_actor(trial)
            self._start_trial_actor(trial)
        elif trial.checkpoint is not None:
            ray_tpu.get(actor.restore.remote(trial.checkpoint), timeout=60)

    # -- lifecycle --

    def _next_trial(self) -> Optional[Trial]:
        if self._searcher_exhausted:
            return None
        if self.num_samples_cap is not None and len(self.trials) >= self.num_samples_cap:
            return None
        trial_id = f"{len(self.trials):05d}"
        config = self.searcher.suggest(trial_id)
        if config is None:
            if not isinstance(self.searcher, Searcher) or not getattr(
                self.searcher, "max_concurrent", 0
            ):
                self._searcher_exhausted = (
                    len(self._live_suggestions()) == 0
                )
            return None
        trial = Trial(
            self.trainable_name,
            config,
            trial_id=trial_id,
            experiment_dir=self.experiment_dir,
            stopping_criteria=self.stopping_criteria,
            max_failures=self.max_failures,
        )
        self.trials.append(trial)
        self.scheduler.on_trial_add(self, trial)
        for cb in self.callbacks:
            self._fire(cb, "on_trial_add", trial=trial)
        return trial

    def _live_suggestions(self) -> list[Trial]:
        return [t for t in self.trials if t.status in (PENDING, RUNNING, PAUSED)]

    def _start_trial_actor(self, trial: Trial) -> None:
        actor = _TrialActor.options(
            num_cpus=self.resources_per_trial.get("CPU", 1),
            resources={
                k: v for k, v in self.resources_per_trial.items() if k != "CPU"
            } or None,
        ).remote(self.trainable_cls, trial.config)
        self._actors[trial.trial_id] = actor
        if trial.checkpoint is not None:
            ray_tpu.get(actor.restore.remote(trial.checkpoint), timeout=120)
        trial.set_status(RUNNING)
        self._futures[actor.train.remote()] = trial

    def _kill_actor(self, trial: Trial) -> None:
        actor = self._actors.pop(trial.trial_id, None)
        if actor is None:
            return
        try:
            ray_tpu.get(actor.stop.remote(), timeout=5)
        except Exception:  # rtlint: disable=swallowed-exception - stop timed out; kill follows
            pass
        try:
            ray_tpu.kill(actor)
        except Exception:  # rtlint: disable=swallowed-exception - actor already dead
            pass

    def _drop_pending_future(self, trial: Trial) -> None:
        for ref, t in list(self._futures.items()):
            if t is trial:
                del self._futures[ref]

    def _running_count(self) -> int:
        return sum(1 for t in self.trials if t.status == RUNNING)

    # -- the event loop --

    def step(self) -> None:
        # 1. top up trials from the searcher
        while self._running_count() < self._max_concurrent:
            pending = [t for t in self.trials if t.status == PENDING]
            if not pending:
                created = self._next_trial()
                if created is None:
                    break
            choice = self.scheduler.choose_trial_to_run(self)
            if choice is None:
                break
            self._start_trial_actor(choice)

        if not self._futures:
            return

        # 2. consume completed train() futures
        ready, _ = ray_tpu.wait(
            list(self._futures), num_returns=1, timeout=1.0
        )
        for ref in ready:
            trial = self._futures.pop(ref)
            try:
                result = ray_tpu.get(ref, timeout=60)
            except Exception as exc:
                self._handle_trial_error(trial, exc)
                continue
            self._handle_result(trial, result)

    def _handle_result(self, trial: Trial, result: dict) -> None:
        trial.iteration = result.get("training_iteration", trial.iteration + 1)
        if "__checkpoint__" in result:
            trial.checkpoint = result.pop("__checkpoint__")
            trial.checkpoint_iter = trial.iteration
            trial.persist_checkpoint()
        # Merge over previous metrics: the function-API's final sentinel
        # ({done: True} with only bookkeeping keys) must not erase the last
        # real report — the reference attaches done to the last result too.
        bookkeeping = {"done", "training_iteration", "time_total_s"}
        trial.last_result = {**trial.last_result, **result}
        if set(result) - bookkeeping:
            trial.metric_history.append(result)
        self.searcher.on_trial_result(trial.trial_id, result)
        for cb in self.callbacks:
            self._fire(cb, "on_trial_result", trial=trial, result=result)

        done = bool(result.get("done")) or trial.should_stop(result)
        decision = TrialScheduler.CONTINUE
        if not done:
            decision = self.scheduler.on_trial_result(self, trial, result)

        checkpoint_now = (
            self.checkpoint_freq
            and trial.iteration - trial.checkpoint_iter >= self.checkpoint_freq
        )
        if (checkpoint_now or done or decision != TrialScheduler.CONTINUE) and (
            actor := self._actors.get(trial.trial_id)
        ):
            try:
                ckpt = ray_tpu.get(actor.save.remote(), timeout=60)
                if ckpt is not None:
                    trial.checkpoint = ckpt
                    trial.checkpoint_iter = trial.iteration
                    trial.persist_checkpoint()
            except Exception:
                # A missed save costs resume granularity, not correctness —
                # but a silently failing one costs the whole experiment.
                logger.warning(
                    "checkpointing trial %s failed", trial.trial_id,
                    exc_info=True,
                )

        if done:
            self._complete_trial(trial, result)
        elif decision == TrialScheduler.STOP:
            self._complete_trial(trial, result, early_stopped=True)
        elif decision == TrialScheduler.PAUSE:
            trial.set_status(PAUSED)
            self._kill_actor(trial)
        else:
            actor = self._actors.get(trial.trial_id)
            if actor is not None:
                self._futures[actor.train.remote()] = trial
        self._save_experiment_state()

    def _complete_trial(
        self, trial: Trial, result: dict, early_stopped: bool = False
    ) -> None:
        trial.set_status(TERMINATED)
        self._drop_pending_future(trial)
        self._kill_actor(trial)
        self.searcher.on_trial_complete(trial.trial_id, result)
        self.scheduler.on_trial_complete(self, trial, result)
        for cb in self.callbacks:
            self._fire(cb, "on_trial_complete", trial=trial, result=result)

    def _handle_trial_error(self, trial: Trial, exc: Exception) -> None:
        trial.num_failures += 1
        trial.error_message = "".join(
            traceback.format_exception_only(type(exc), exc)
        ).strip()
        self._drop_pending_future(trial)
        self._kill_actor(trial)
        if trial.num_failures <= trial.max_failures:
            trial.set_status(ERROR)
            trial.set_status(PENDING)  # retry (restores from checkpoint)
        else:
            trial.set_status(ERROR)
            self.searcher.on_trial_complete(trial.trial_id, error=True)
            self.scheduler.on_trial_error(self, trial)
            for cb in self.callbacks:
                self._fire(cb, "on_trial_error", trial=trial)
        self._save_experiment_state()

    def run(self) -> list[Trial]:
        start = time.time()
        while True:
            self.step()
            if self.time_budget_s and time.time() - start > self.time_budget_s:
                for trial in self.live_trials:
                    self._drop_pending_future(trial)
                    self._kill_actor(trial)
                    trial.set_status(TERMINATED)
                break
            if not self._futures and all(
                t.is_finished() or t.status == PAUSED for t in self.trials
            ):
                paused = [t for t in self.trials if t.status == PAUSED]
                if paused and self._running_count() < self._max_concurrent:
                    continue  # scheduler may resume paused trials next step
                if self._searcher_exhausted or (
                    self.num_samples_cap is not None
                    and len(self.trials) >= self.num_samples_cap
                ):
                    break
                if self._next_trial() is None:
                    break
        self._save_experiment_state()
        return self.trials

    @staticmethod
    def _fire(cb, hook: str, **kwargs) -> None:
        handler = getattr(cb, hook, None)
        if handler:
            try:
                handler(**kwargs)
            except Exception:
                # User callbacks must not kill the trial loop, but their
                # bugs must not vanish either (reference logs these too).
                logger.warning("callback %s raised", hook, exc_info=True)

    # -- experiment state (Tuner.restore) --

    def _save_experiment_state(self) -> None:
        state = {
            "trainable_name": self.trainable_name,
            "metric": self.metric,
            "mode": self.mode,
            "searcher": self._try(self.searcher.save),
            "trials": [t.to_json() for t in self.trials],
        }
        path = os.path.join(self.experiment_dir, EXPERIMENT_STATE_FILE)
        try:
            atomic_io.atomic_write_json(path, state, default=str)
        except TypeError:  # rtlint: disable=swallowed-exception - unserializable user state: restore restarts fresh, by design
            pass

    @staticmethod
    def _try(fn):
        try:
            return fn()
        except Exception:  # rtlint: disable=swallowed-exception - probe helper: callers treat None as unavailable
            return None

    def restore_experiment_state(self, resume_errored: bool = False) -> None:
        path = os.path.join(self.experiment_dir, EXPERIMENT_STATE_FILE)
        if not os.path.exists(path):
            return
        with open(path) as f:
            state = json.load(f)
        if state.get("searcher") is not None:
            self._try(lambda: self.searcher.restore(state["searcher"]))
        for tdata in state["trials"]:
            trial = Trial.from_json(tdata, self.experiment_dir)
            if trial.status == ERROR and resume_errored:
                trial.num_failures = 0
                trial.set_status(PENDING)
            self.trials.append(trial)
            self.scheduler.on_trial_add(self, trial)

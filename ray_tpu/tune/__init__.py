"""ray_tpu.tune — hyperparameter search (Ray Tune-equivalent).

Entry points mirror ray.tune: Tuner(...).fit() → ResultGrid, tune.run(...),
search-space constructors (uniform/choice/grid_search/...), schedulers
(ASHA/HyperBand/PBT/median-stopping), searchers (grid/random, Optuna
adapter), function trainables with tune.report(), class Trainables, and
experiment resume via Tuner.restore(). SURVEY §2.5.
"""

from ray_tpu.tune.result_grid import ResultGrid, TrialResult
from ray_tpu.tune.search.sample import (
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (
    Trainable,
    get_checkpoint,
    report,
    with_parameters,
    wrap_function,
)
from ray_tpu.tune.tuner import TuneConfig, Tuner, run

__all__ = [
    "Tuner",
    "TuneConfig",
    "run",
    "ResultGrid",
    "TrialResult",
    "Trainable",
    "report",
    "get_checkpoint",
    "with_parameters",
    "wrap_function",
    "uniform",
    "loguniform",
    "quniform",
    "randint",
    "lograndint",
    "choice",
    "randn",
    "sample_from",
    "grid_search",
]

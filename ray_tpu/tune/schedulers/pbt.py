"""Population Based Training.

Role-equivalent of python/ray/tune/schedulers/pbt.py ::
PopulationBasedTraining. At every `perturbation_interval` along each trial's
time axis: bottom-quantile trials EXPLOIT a top-quantile trial (copy its
checkpoint + config) then EXPLORE (mutate hyperparameters by 1.2/0.8
perturbation or resample). Checkpoint transfer rides the object store via
the trial actors' save()/restore() (SURVEY §2.5).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Mapping

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.search.sample import Domain


class PopulationBasedTraining(TrialScheduler):
    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str | None = None,
        perturbation_interval: float = 10.0,
        hyperparam_mutations: Mapping[str, Any] | None = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        perturbation_factors: tuple[float, float] = (1.2, 0.8),
        custom_explore_fn: Callable[[dict], dict] | None = None,
        seed: int | None = None,
    ):
        if not hyperparam_mutations and custom_explore_fn is None:
            raise ValueError(
                "PBT needs hyperparam_mutations and/or custom_explore_fn"
            )
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.perturbation_interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile_fraction = quantile_fraction
        self.resample_probability = resample_probability
        self.perturbation_factors = perturbation_factors
        self.custom_explore_fn = custom_explore_fn
        self._rng = random.Random(seed)
        self._last_perturb: dict[str, float] = {}
        self._scores: dict[str, float] = {}
        self.num_perturbations = 0

    def _signed(self, result: dict) -> float:
        value = result[self.metric]
        return value if self.mode == "max" else -value

    def _quantiles(self, controller) -> tuple[list, list]:
        """(bottom, top) trials by latest score; only trials that reported."""
        scored = [
            t for t in controller.live_trials if t.trial_id in self._scores
        ]
        scored.sort(key=lambda t: self._scores[t.trial_id])
        if len(scored) <= 1:
            return [], []
        k = max(1, int(len(scored) * self.quantile_fraction))
        if 2 * k > len(scored):
            k = len(scored) // 2
        return scored[:k], scored[-k:]

    def explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_probability or key not in new:
                if isinstance(spec, Domain):
                    new[key] = spec.sample(self._rng)
                elif isinstance(spec, (list, tuple)):
                    new[key] = self._rng.choice(list(spec))
                elif callable(spec):
                    new[key] = spec()
            elif isinstance(new[key], (int, float)) and not isinstance(new[key], bool):
                factor = self._rng.choice(self.perturbation_factors)
                mutated = new[key] * factor
                new[key] = type(new[key])(mutated) if isinstance(new[key], int) else mutated
            elif isinstance(spec, (list, tuple)):
                # Non-numeric: step to a neighbouring listed value.
                values = list(spec)
                if new[key] in values:
                    idx = values.index(new[key])
                    shift = self._rng.choice((-1, 1))
                    new[key] = values[max(0, min(len(values) - 1, idx + shift))]
        if self.custom_explore_fn:
            new = self.custom_explore_fn(new)
        return new

    def on_trial_add(self, controller, trial) -> None:
        self._last_perturb[trial.trial_id] = 0.0

    def on_trial_result(self, controller, trial, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return self.CONTINUE
        self._scores[trial.trial_id] = self._signed(result)
        t = result[self.time_attr]
        if t - self._last_perturb.get(trial.trial_id, 0) < self.perturbation_interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        bottom, top = self._quantiles(controller)
        if trial in bottom and top:
            donor = self._rng.choice(top)
            self._exploit(controller, trial, donor)
        return self.CONTINUE

    def _exploit(self, controller, trial, donor) -> None:
        """Copy donor's checkpoint + explored config into `trial`."""
        self.num_perturbations += 1
        new_config = self.explore(donor.config)
        controller.transplant_trial(trial, donor, new_config)

    def on_trial_complete(self, controller, trial, result: dict) -> None:
        self._scores.pop(trial.trial_id, None)

    def debug_string(self) -> str:
        return f"PBT: {self.num_perturbations} perturbations"

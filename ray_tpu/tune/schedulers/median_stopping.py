"""Median stopping rule.

Role-equivalent of python/ray/tune/schedulers/median_stopping_rule.py ::
MedianStoppingRule — stop a trial at time t if its best result so far is
worse than the median of other trials' running averages at t.
"""

from __future__ import annotations

import statistics

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str | None = None,
        grace_period: float = 1.0,
        min_samples_required: int = 3,
        min_time_slice: float = 0.0,
        hard_stop: bool = True,
    ):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples_required = min_samples_required
        self.min_time_slice = min_time_slice
        self.hard_stop = hard_stop
        # trial_id -> list[(t, signed value)]
        self._results: dict[str, list[tuple[float, float]]] = {}
        self._num_stopped = 0

    def _signed(self, result: dict) -> float:
        value = result[self.metric]
        return value if self.mode == "max" else -value

    def _running_mean_at(self, trial_id: str, t: float) -> float | None:
        points = [v for (pt, v) in self._results.get(trial_id, []) if pt <= t]
        return statistics.fmean(points) if points else None

    def on_trial_result(self, controller, trial, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return self.CONTINUE
        t = result[self.time_attr]
        self._results.setdefault(trial.trial_id, []).append(
            (t, self._signed(result))
        )
        if t < self.grace_period:
            return self.CONTINUE
        other_means = [
            m
            for other_id in self._results
            if other_id != trial.trial_id
            and (m := self._running_mean_at(other_id, t)) is not None
        ]
        if len(other_means) < self.min_samples_required:
            return self.CONTINUE
        median = statistics.median(other_means)
        best = max(v for _, v in self._results[trial.trial_id])
        if best < median:
            self._num_stopped += 1
            return self.STOP if self.hard_stop else self.PAUSE
        return self.CONTINUE

    def debug_string(self) -> str:
        return f"MedianStoppingRule: {self._num_stopped} stopped"

"""Trial scheduler protocol.

Role-equivalent of python/ray/tune/schedulers/trial_scheduler.py ::
TrialScheduler / FIFOScheduler. Schedulers see every intermediate result and
decide CONTINUE / PAUSE / STOP; the controller enforces the decision.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.tune.experiment.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    metric: str | None = None
    mode: str | None = None

    def set_search_properties(self, metric: str | None, mode: str | None) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_add(self, controller, trial: "Trial") -> None:
        pass

    def on_trial_result(self, controller, trial: "Trial", result: dict) -> str:
        return self.CONTINUE

    def on_trial_complete(self, controller, trial: "Trial", result: dict) -> None:
        pass

    def on_trial_error(self, controller, trial: "Trial") -> None:
        pass

    def choose_trial_to_run(self, controller) -> "Trial | None":
        """Pick the next PENDING/PAUSED trial to (re)start, or None."""
        for trial in controller.live_trials:
            if trial.status == "PENDING":
                return trial
        for trial in controller.live_trials:
            if trial.status == "PAUSED":
                return trial
        return None

    def debug_string(self) -> str:
        return type(self).__name__


class FIFOScheduler(TrialScheduler):
    """Run trials to completion in submission order."""

from ray_tpu.tune.schedulers.asha import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    HyperBandScheduler,
)
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining
from ray_tpu.tune.schedulers.trial_scheduler import FIFOScheduler, TrialScheduler

__all__ = [
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
]

"""Asynchronous Successive Halving (ASHA) + synchronous HyperBand.

Role-equivalents of python/ray/tune/schedulers/async_hyperband.py ::
AsyncHyperBandScheduler (alias ASHAScheduler) and hyperband.py ::
HyperBandScheduler. The rung math here is pure (no actors) so it is
table-testable exactly like the reference's test_trial_scheduler.py drives
it with fabricated results (SURVEY §4.3).
"""

from __future__ import annotations

import math

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _Bracket:
    """One ASHA bracket: rungs at r, r·η, r·η², … ≤ max_t. A trial stops at
    a rung unless its metric is in the top 1/η of recorded values there."""

    def __init__(self, min_t: float, max_t: float, reduction_factor: float, s: int):
        self.rf = reduction_factor
        self._rungs: list[tuple[float, dict]] = [
            (min_t * self.rf ** (k + s), {})
            for k in reversed(range(int(math.log(max_t / min_t) / math.log(self.rf) - s + 1)))
        ]

    def cutoff(self, recorded: dict) -> float | None:
        if not recorded:
            return None
        values = sorted(recorded.values())
        k = int(len(values) * (1 - 1 / self.rf))
        return values[min(k, len(values) - 1)]

    def on_result(self, trial_id: str, cur_t: float, metric_value: float) -> str:
        action = TrialScheduler.CONTINUE
        for milestone, recorded in self._rungs:
            if cur_t < milestone or trial_id in recorded:
                continue
            cutoff = self.cutoff(recorded)
            if cutoff is not None and metric_value < cutoff:
                action = TrialScheduler.STOP
            recorded[trial_id] = metric_value
            break
        return action

    def debug_string(self) -> str:
        rungs = ", ".join(
            f"t={m:.0f}:{len(r)}" for m, r in self._rungs
        )
        return f"Bracket({rungs})"


class ASHAScheduler(TrialScheduler):
    """Async successive halving: aggressive early stopping without waiting
    for rungs to fill. The default Tune scheduler for sweeps."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str | None = None,
        max_t: float = 100,
        grace_period: float = 1,
        reduction_factor: float = 4,
        brackets: int = 1,
    ):
        if grace_period < 1:
            raise ValueError("grace_period must be >= 1")
        if reduction_factor <= 1:
            raise ValueError("reduction_factor must be > 1")
        if max_t < grace_period:
            raise ValueError("max_t must be >= grace_period")
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self._brackets = [
            _Bracket(grace_period, max_t, reduction_factor, s)
            for s in range(brackets)
        ]
        self._trial_bracket: dict[str, _Bracket] = {}
        self._counter = 0
        self._num_stopped = 0

    def _signed(self, result: dict) -> float:
        value = result[self.metric]
        return value if self.mode == "max" else -value

    def on_trial_add(self, controller, trial) -> None:
        # Round-robin over brackets (reference uses softmax over sizes;
        # round-robin gives the same asymptotic occupancy deterministically).
        bracket = self._brackets[self._counter % len(self._brackets)]
        self._counter += 1
        self._trial_bracket[trial.trial_id] = bracket

    def on_trial_result(self, controller, trial, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return self.CONTINUE
        cur_t = result[self.time_attr]
        if cur_t >= self.max_t:
            return self.STOP
        action = self._trial_bracket[trial.trial_id].on_result(
            trial.trial_id, cur_t, self._signed(result)
        )
        if action == self.STOP:
            self._num_stopped += 1
        return action

    def on_trial_complete(self, controller, trial, result: dict) -> None:
        if self.metric not in result or self.time_attr not in result:
            return
        self._trial_bracket[trial.trial_id].on_result(
            trial.trial_id, result[self.time_attr], self._signed(result)
        )

    def debug_string(self) -> str:
        lines = [f"ASHA: {self._num_stopped} stopped early"]
        lines += [b.debug_string() for b in self._brackets]
        return "\n".join(lines)


# Reference alias
AsyncHyperBandScheduler = ASHAScheduler


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand: ASHA brackets but halving waits for the rung
    to fill. Implemented on the same rung table; "synchronous" here means a
    rung only evicts once it holds `reduction_factor` entries, which the
    cutoff math already guarantees (cutoff is None below that)."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str | None = None,
        max_t: float = 81,
        reduction_factor: float = 3,
    ):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        self._inner = ASHAScheduler(
            time_attr=time_attr,
            metric=metric,
            mode=mode,
            max_t=max_t,
            grace_period=1,
            reduction_factor=reduction_factor,
            brackets=s_max + 1,
        )

    def set_search_properties(self, metric, mode) -> bool:
        self._inner.set_search_properties(metric, mode)
        return super().set_search_properties(metric, mode)

    def on_trial_add(self, controller, trial) -> None:
        self._inner.on_trial_add(controller, trial)

    def on_trial_result(self, controller, trial, result: dict) -> str:
        return self._inner.on_trial_result(controller, trial, result)

    def on_trial_complete(self, controller, trial, result: dict) -> None:
        self._inner.on_trial_complete(controller, trial, result)

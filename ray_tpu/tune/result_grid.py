"""ResultGrid — what Tuner.fit() returns.

Role-equivalent of python/ray/tune/result_grid.py :: ResultGrid +
analysis/experiment_analysis.py best-trial selection.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.tune.experiment.trial import ERROR, Trial


@dataclass
class TrialResult:
    trial_id: str
    config: dict
    metrics: dict
    error: Optional[str] = None
    checkpoint: Any = None
    path: str = ""
    metrics_history: list = field(default_factory=list)

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.metrics_history)


class ResultGrid:
    def __init__(self, trials: list[Trial], metric: str | None, mode: str | None):
        self._trials = trials
        self._metric = metric
        self._mode = mode
        self._results = [
            TrialResult(
                trial_id=t.trial_id,
                config=t.config,
                metrics=t.last_result,
                error=t.error_message if t.status == ERROR else None,
                checkpoint=t.checkpoint,
                path=t.local_dir,
                metrics_history=t.metric_history,
            )
            for t in trials
        ]

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, idx: int) -> TrialResult:
        return self._results[idx]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> list[str]:
        return [r.error for r in self._results if r.error]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    def get_best_result(
        self,
        metric: str | None = None,
        mode: str | None = None,
        scope: str = "last",
    ) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode or "max"
        if metric is None:
            raise ValueError("no metric given to get_best_result")
        sign = 1 if mode == "max" else -1

        def score(r: TrialResult) -> float:
            if scope == "all" and r.metrics_history:
                values = [
                    m[metric] for m in r.metrics_history if metric in m
                ]
                if values:
                    return sign * max(sign * v for v in values)
            if metric in r.metrics:
                return sign * r.metrics[metric]
            return float("-inf")

        candidates = [r for r in self._results if not r.error]
        if not candidates:
            raise RuntimeError("all trials errored")
        return max(candidates, key=score)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["trial_id"] = r.trial_id
            for key, value in r.config.items():
                row[f"config/{key}"] = value
            rows.append(row)
        return pd.DataFrame(rows)

"""Trainable — the unit of execution Tune schedules.

Role-equivalent of python/ray/tune/trainable/trainable.py :: Trainable and
function_trainable.py :: wrap_function. Two API shapes, same as the
reference:

  * class API — subclass Trainable, implement setup/step/save_checkpoint/
    load_checkpoint; the controller calls train() per iteration.
  * function API — def train_fn(config): ... ray_tpu.tune.report(...) —
    wrapped into a Trainable that runs the function on a background thread
    and hands results over a rendezvous queue (one result per train() call),
    mirroring the reference's FunctionTrainable/_StatusReporter design.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Any, Callable, Optional

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self._iteration = 0
        self._start_time = time.time()
        self.setup(self.config)

    # -- subclass surface --
    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        """Return a picklable blob capturing trainable state."""
        return None

    def load_checkpoint(self, checkpoint: Any) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """In-place config swap (PBT explore). False = controller must
        recreate the actor instead."""
        return False

    def cleanup(self) -> None:
        pass

    # -- controller surface (remote-invoked) --
    def train(self) -> dict:
        result = self.step() or {}
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault("time_total_s", time.time() - self._start_time)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Any:
        return self.save_checkpoint()

    def restore(self, checkpoint: Any) -> None:
        self.load_checkpoint(checkpoint)

    def reset(self, new_config: dict) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
        return ok

    def stop(self) -> None:
        self.cleanup()


class _Session:
    """Per-trial function-API session: report() rendezvous + checkpointing.

    The function thread blocks in report() until the controller consumes the
    result via train() — preserving the reference's lockstep semantics so
    schedulers can pause/stop between iterations.
    """

    def __init__(self, config: dict, checkpoint: Any = None):
        self.config = config
        self.loaded_checkpoint = checkpoint
        self.saved_checkpoint: Any = None
        self._results: queue.Queue = queue.Queue(maxsize=1)
        self._pending_ckpt: Any = None
        self._consumed = threading.Event()
        self._consumed.set()
        self._stop = threading.Event()

    def report(self, metrics: dict, checkpoint: Any = None) -> None:
        if self._stop.is_set():
            raise StopIteration("trial stopped")
        if checkpoint is not None:
            self.saved_checkpoint = checkpoint
            self._pending_ckpt = checkpoint
        self._results.put(dict(metrics))
        self._consumed.wait()
        self._consumed.clear()
        if self._stop.is_set():
            raise StopIteration("trial stopped")

    def get_checkpoint(self) -> Any:
        return self.loaded_checkpoint


_session_lock = threading.Lock()
_current_session: Optional[_Session] = None


def _set_session(session: Optional[_Session]) -> None:
    global _current_session
    with _session_lock:
        _current_session = session


def report(metrics: dict, *, checkpoint: Any = None) -> None:
    """ray_tpu.tune.report — called from inside a function trainable."""
    if _current_session is None:
        raise RuntimeError("tune.report() called outside a Tune session")
    _current_session.report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Any:
    if _current_session is None:
        return None
    return _current_session.get_checkpoint()


def wrap_function(train_fn: Callable[[dict], Any]) -> type:
    """Build a Trainable class around a function trainable."""

    class FunctionTrainable(Trainable):
        _name = getattr(train_fn, "__name__", "func")

        def setup(self, config: dict) -> None:
            self._session = _Session(config)
            self._thread: threading.Thread | None = None
            self._error: list[BaseException] = []
            self._fn_done = threading.Event()

        def _runner(self) -> None:
            _set_session(self._session)
            try:
                train_fn(self.config)
            except StopIteration:
                pass
            except BaseException as exc:  # surfaces via train()
                exc._tb = traceback.format_exc()  # type: ignore
                self._error.append(exc)
            finally:
                self._fn_done.set()
                _set_session(None)

        def _ensure_thread(self) -> None:
            if self._thread is None:
                self._thread = threading.Thread(target=self._runner, daemon=True)
                self._thread.start()

        def step(self) -> dict:
            self._ensure_thread()
            while True:
                try:
                    metrics = self._session._results.get(timeout=0.05)
                    # A checkpoint reported alongside metrics rides the
                    # result dict so the controller can persist it even
                    # without a checkpoint_freq-triggered save().
                    if self._session._pending_ckpt is not None:
                        metrics["__checkpoint__"] = self._session._pending_ckpt
                        self._session._pending_ckpt = None
                    self._session._consumed.set()
                    return metrics
                except queue.Empty:
                    if self._error:
                        raise self._error[0]
                    if self._fn_done.is_set():
                        return {DONE: True}

        def save_checkpoint(self) -> Any:
            return self._session.saved_checkpoint

        def load_checkpoint(self, checkpoint: Any) -> None:
            self._session.loaded_checkpoint = checkpoint

        def cleanup(self) -> None:
            self._session._stop.set()
            self._session._consumed.set()

    FunctionTrainable.__name__ = f"func_{getattr(train_fn, '__name__', 'trainable')}"
    return FunctionTrainable


def with_parameters(fn: Callable, **params) -> Callable:
    """ray.tune.with_parameters-equivalent: close large objects over the
    trainable without putting them in the config dict."""

    def wrapped(config: dict):
        return fn(config, **params)

    wrapped.__name__ = getattr(fn, "__name__", "trainable")
    return wrapped

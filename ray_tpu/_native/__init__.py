"""Build + load the native runtime library (libraytpu.so).

The C++ sources live in ``src/`` at the repo root. We compile them on first
import (cached by source mtime) — the environment guarantees g++. This keeps
the native components buildable without a packaging step, like the
reference's bazel-built core but without requiring bazel at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC_DIRS = [
    os.path.join(_REPO, "src", "object_store"),
    os.path.join(_REPO, "src", "rpc"),
]
_LIB_PATH = os.path.join(_HERE, "libraytpu.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _sources() -> list[str]:
    out: list[str] = []
    for d in _SRC_DIRS:
        if os.path.isdir(d):
            out.extend(
                os.path.join(d, f) for f in sorted(os.listdir(d)) if f.endswith(".cc")
            )
    return out


def _needs_build(sources: list[str]) -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in sources)


def build(force: bool = False) -> str:
    sources = _sources()
    if not sources:
        raise RuntimeError(f"no native sources found under {_SRC_DIRS}")
    if force or _needs_build(sources):
        cmd = [
            "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
            "-o", _LIB_PATH, *sources,
        ]
        # rtlint: disable=blocking-in-async - one-time lazy toolchain compile, memoized on source mtimes; cold-start only, never on the steady-state loop
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            path = build()
            lib = ctypes.CDLL(path)
            lib.raytpu_store_start.restype = ctypes.c_void_p
            lib.raytpu_store_start.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ]
            lib.raytpu_store_stop.argtypes = [ctypes.c_void_p]
            # --- rpc transport (src/rpc/transport.cc) ---
            lib.rt_engine_new.restype = ctypes.c_void_p
            lib.rt_engine_stop.argtypes = [ctypes.c_void_p]
            lib.rt_notify_fd.argtypes = [ctypes.c_void_p]
            lib.rt_notify_fd.restype = ctypes.c_int
            lib.rt_connect_tcp.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ]
            lib.rt_connect_tcp.restype = ctypes.c_long
            lib.rt_connect_unix.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_connect_unix.restype = ctypes.c_long
            lib.rt_listen_tcp.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.rt_listen_tcp.restype = ctypes.c_long
            lib.rt_listen_unix.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_listen_unix.restype = ctypes.c_long
            lib.rt_next_msgid.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rt_next_msgid.restype = ctypes.c_uint32
            lib.rt_send.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_uint8,
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.rt_send.restype = ctypes.c_int
            lib.rt_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rt_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.rt_next.restype = ctypes.c_int
            lib.rt_msg_free.argtypes = [ctypes.c_void_p]
            lib.rt_conn_debug.argtypes = [
                ctypes.c_void_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_longlong),
            ]
            lib.rt_conn_debug.restype = ctypes.c_int
            # --- native call table + exec fast lane (hot path, N18-N20) ---
            lib.rt_call_start.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p,
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.rt_call_start.restype = ctypes.c_uint64
            lib.rt_call_start_buf.argtypes = lib.rt_call_start.argtypes
            lib.rt_call_start_buf.restype = ctypes.c_uint64
            lib.rt_send_buf.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_uint8,
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.rt_send_buf.restype = ctypes.c_int
            lib.rt_exec_pending.argtypes = [ctypes.c_void_p]
            lib.rt_exec_pending.restype = ctypes.c_int
            lib.rt_conn_inflight.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rt_conn_inflight.restype = ctypes.c_int
            lib.rt_call_wait.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
                ctypes.c_void_p,
            ]
            lib.rt_call_wait.restype = ctypes.c_int
            lib.rt_call_poll.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ]
            lib.rt_call_poll.restype = ctypes.c_int
            lib.rt_call_abandon.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rt_exec_filter.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_exec_next.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
            ]
            lib.rt_exec_next.restype = ctypes.c_int
            lib.rt_exec_inject.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
            lib.rt_list_conns.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
                ctypes.c_int,
            ]
            lib.rt_list_conns.restype = ctypes.c_int
            # --- object-transfer plane (push manager, N16) ---
            lib.rt_push_object.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p,
                ctypes.c_void_p, ctypes.c_uint64,
            ]
            lib.rt_push_object.restype = ctypes.c_int
            lib.rt_transfer_take.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.rt_transfer_take.restype = ctypes.c_int
            lib.rt_transfer_free.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            # --- native lease lane (raylet grant path, N9/N10) ---
            lib.rt_lease_enable.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_lease_adjust.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_int,
            ]
            lib.rt_lease_adjust.restype = ctypes.c_int
            lib.rt_lease_pool_put.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.rt_lease_pool_pop.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.rt_lease_pool_pop.restype = ctypes.c_int
            lib.rt_lease_pool_remove.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.rt_lease_pool_remove.restype = ctypes.c_int
            lib.rt_lease_worker_ban.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.rt_lease_worker_unban.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.rt_lease_forget.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_lease_forget.restype = ctypes.c_int
            lib.rt_lease_next_event.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ]
            lib.rt_lease_next_event.restype = ctypes.c_int
            lib.rt_lease_available_json.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ]
            lib.rt_lease_available_json.restype = ctypes.c_int
            lib.rt_lease_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ]
            lib.rt_engine_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ]
            _lib = lib
    return _lib


_pylib: "ctypes.PyDLL | None" = None


def load_nogilrelease() -> ctypes.PyDLL:
    """The same library loaded via PyDLL: calls KEEP the GIL.

    For microsecond-scale non-blocking entry points (rt_send on a
    non-blocking fd, rt_next, rt_next_msgid, rt_msg_free) the GIL
    release+reacquire of a normal CDLL call costs more than the call
    itself under thread contention (~150 us measured on a 1-core host vs
    ~10 us of actual work). Never use this handle for anything that can
    block."""
    global _pylib
    with _lock:
        if _pylib is None:
            path = build()
            lib = ctypes.PyDLL(path)
            lib.rt_send.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_uint8,
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.rt_send.restype = ctypes.c_int
            lib.rt_next_msgid.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rt_next_msgid.restype = ctypes.c_uint32
            lib.rt_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.rt_next.restype = ctypes.c_int
            lib.rt_msg_free.argtypes = [ctypes.c_void_p]
            # Non-blocking fast-lane entry points (safe to keep the GIL:
            # rt_call_start's inline send is on a non-blocking fd).
            lib.rt_call_start.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p,
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.rt_call_start.restype = ctypes.c_uint64
            lib.rt_call_start_buf.argtypes = lib.rt_call_start.argtypes
            lib.rt_call_start_buf.restype = ctypes.c_uint64
            lib.rt_send_buf.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_uint8,
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.rt_send_buf.restype = ctypes.c_int
            lib.rt_exec_pending.argtypes = [ctypes.c_void_p]
            lib.rt_exec_pending.restype = ctypes.c_int
            lib.rt_conn_inflight.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rt_conn_inflight.restype = ctypes.c_int
            lib.rt_call_poll.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ]
            lib.rt_call_poll.restype = ctypes.c_int
            lib.rt_call_abandon.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rt_exec_inject.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
            _pylib = lib
    return _pylib


# ---------------------------------------------------------------------------
# _fastlane — CPython extension for the per-task hot path (src/pyext/).
# Built separately from libraytpu.so (it needs Python headers); it attaches
# to the SAME engine library at runtime via dlopen, so the two stay one
# native runtime. Failure to build/load degrades to the ctypes path.
# ---------------------------------------------------------------------------
_FASTLANE_SRC = os.path.join(_REPO, "src", "pyext", "fastlane.cc")
_FASTLANE_PATH = os.path.join(_HERE, "_fastlane.so")
_fastlane_mod = None
_fastlane_failed = False


def build_fastlane(force: bool = False) -> str:
    import sysconfig

    if (
        force
        or not os.path.exists(_FASTLANE_PATH)
        or os.path.getmtime(_FASTLANE_SRC) > os.path.getmtime(_FASTLANE_PATH)
    ):
        cmd = [
            "g++", "-std=c++17", "-O2", "-fPIC", "-shared",
            f"-I{sysconfig.get_paths()['include']}",
            "-o", _FASTLANE_PATH, _FASTLANE_SRC,
        ]
        # rtlint: disable=blocking-in-async - one-time lazy toolchain compile, memoized on source mtimes; cold-start only, never on the steady-state loop
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _FASTLANE_PATH


def load_fastlane():
    """Import the _fastlane extension, attached to the engine lib.
    Returns the module, or None when disabled/unbuildable."""
    global _fastlane_mod, _fastlane_failed
    if _fastlane_mod is not None:
        return _fastlane_mod
    if _fastlane_failed or os.environ.get("RAY_TPU_fastlane") == "0":
        return None
    with _lock:
        if _fastlane_mod is not None:
            return _fastlane_mod
        try:
            import importlib.util

            lib_path = build()
            ext_path = build_fastlane()
            spec = importlib.util.spec_from_file_location(
                "ray_tpu._native._fastlane", ext_path
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            mod.attach(lib_path)
            _fastlane_mod = mod
        except Exception:
            _fastlane_failed = True
            return None
    return _fastlane_mod


class RtMsgView(ctypes.Structure):
    """Mirror of rt_msg_view in src/rpc/transport.cc."""

    _fields_ = [
        ("conn", ctypes.c_long),
        ("kind", ctypes.c_uint8),
        ("msgid", ctypes.c_uint32),
        ("method", ctypes.c_void_p),
        ("mlen", ctypes.c_uint32),
        ("payload", ctypes.c_void_p),
        ("plen", ctypes.c_uint32),
        ("opaque", ctypes.c_void_p),
    ]

"""Build + load the native runtime library (libraytpu.so).

The C++ sources live in ``src/`` at the repo root. We compile them on first
import (cached by source mtime) — the environment guarantees g++. This keeps
the native components buildable without a packaging step, like the
reference's bazel-built core but without requiring bazel at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC_DIRS = [os.path.join(_REPO, "src", "object_store")]
_LIB_PATH = os.path.join(_HERE, "libraytpu.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _sources() -> list[str]:
    out: list[str] = []
    for d in _SRC_DIRS:
        if os.path.isdir(d):
            out.extend(
                os.path.join(d, f) for f in sorted(os.listdir(d)) if f.endswith(".cc")
            )
    return out


def _needs_build(sources: list[str]) -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in sources)


def build(force: bool = False) -> str:
    sources = _sources()
    if not sources:
        raise RuntimeError(f"no native sources found under {_SRC_DIRS}")
    if force or _needs_build(sources):
        cmd = [
            "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
            "-o", _LIB_PATH, *sources,
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            path = build()
            lib = ctypes.CDLL(path)
            lib.raytpu_store_start.restype = ctypes.c_void_p
            lib.raytpu_store_start.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ]
            lib.raytpu_store_stop.argtypes = [ctypes.c_void_p]
            _lib = lib
    return _lib

#!/usr/bin/env bash
# Run the compiled-DAG (rtdag) suite (ISSUE 15).
#
# Tier-1 CI runs `pytest -m 'not slow'`, which already covers the graph
# builder, the placement plan, fan-out/fan-in ordering, backpressure at
# ring depth, device-vs-shm channel parity, teardown leak checks, the
# zero-controller-RPC steady state, the commgraph DAG-wire fixtures,
# and the chaos kill e2e (typed DAGActorDiedError + hang report naming
# the dead rank). This script is the nightly companion that re-runs
# that subset and then executes the compiled_dag_overhead release
# benchmark in smoke mode, enforcing the acceptance gates
# (hop_overhead_pct within bound, rpc_ratio>=10, dag_controller_rpcs==0)
# via release/run_all.py.
# Usage: ci/run_dag_bench.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== compiled DAG suite (unit + e2e) =="
python -m pytest tests/test_dag.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== DAG chaos e2e (typed death + hang doctor) =="
python -m pytest tests/test_dag_chaos.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== commgraph certifies DAG wires =="
python -m ray_tpu lint --comm-graph

echo "== compiled DAG release benchmark (smoke, gated) =="
python release/run_all.py --smoke --only compiled_dag_overhead

echo "compiled DAG suite: PASS"

#!/usr/bin/env bash
# rtlint gate: framework-aware static analysis over the whole repo.
#
# Fails on any finding NOT in the committed baseline
# (.rtlint-baseline.json) and on stale baseline entries — new
# distributed-system hazards (blocking calls on async paths,
# rank-divergent collectives, non-atomic state-file writes, swallowed
# exceptions, lock-order cycles, host syncs in step functions, and the
# ISSUE-12 protocol errors: unmatched p2p wires, tag collisions,
# rank-asymmetric channels, deadlocking schedule grids) cannot land,
# while the documented-debt ledger only shrinks. SARIF + commgraph DOT
# artifacts are written next to the human report.
#
# PR fast path: when RTLINT_CHANGED_ONLY=1 (or a base ref is given via
# RTLINT_BASE_REF), a quick per-file pass runs FIRST over just the
# changed .py files for fast reviewer feedback. The full-repo run with
# the commgraph rules remains the BLOCKING gate either way — protocol
# matching is whole-program, so a changed-files-only verdict can never
# be authoritative (deleting a recv leaves the stale send in an
# unchanged file).
# Usage: ci/run_lint.sh [extra `ray_tpu lint` args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
ARTIFACT_DIR="${RTLINT_ARTIFACT_DIR:-/tmp/rtlint}"
mkdir -p "$ARTIFACT_DIR"

if [[ "${RTLINT_CHANGED_ONLY:-0}" == "1" || -n "${RTLINT_BASE_REF:-}" ]]; then
    BASE_REF="${RTLINT_BASE_REF:-origin/main}"
    echo "== rtlint (changed-files fast path vs ${BASE_REF}) =="
    mapfile -t CHANGED < <(
        git diff --name-only --diff-filter=d "${BASE_REF}...HEAD" -- \
            '*.py' 2>/dev/null || true
    )
    if (( ${#CHANGED[@]} )); then
        # Advisory speed pass: surfaces per-file findings in seconds.
        # Cross-file rules see only this slice here, hence the full
        # blocking gate below.
        python -m ray_tpu lint "${CHANGED[@]}" || true
    else
        echo "rtlint fast path: no changed .py files"
    fi
fi

echo "== rtlint (full-repo blocking gate) =="
# Always emit the SARIF artifact, even on a failing run — code scanning
# wants the findings, not just the exit code. The gating pass below
# also exports the communication channel graph for the PR artifacts.
python -m ray_tpu lint --format sarif --out "$ARTIFACT_DIR/rtlint.sarif" "$@" \
    || true
python -m ray_tpu lint --comm-graph \
    --comm-graph-out "$ARTIFACT_DIR/commgraph.dot" "$@"

echo "rtlint gate: PASS (sarif: $ARTIFACT_DIR/rtlint.sarif, commgraph: $ARTIFACT_DIR/commgraph.dot)"

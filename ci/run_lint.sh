#!/usr/bin/env bash
# rtlint gate: framework-aware static analysis over the whole repo.
#
# Fails on any finding NOT in the committed baseline
# (.rtlint-baseline.json) and on stale baseline entries — new
# distributed-system hazards (blocking calls on async paths,
# rank-divergent collectives, non-atomic state-file writes, swallowed
# exceptions, lock-order cycles, host syncs in step functions) cannot
# land, while the documented-debt ledger only shrinks. A SARIF artifact
# is written next to the human report for code-scanning ingestion.
# Usage: ci/run_lint.sh [extra `ray_tpu lint` args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
ARTIFACT_DIR="${RTLINT_ARTIFACT_DIR:-/tmp/rtlint}"
mkdir -p "$ARTIFACT_DIR"

echo "== rtlint (baseline-diff gate) =="
# Always emit the SARIF artifact, even on a failing run — code scanning
# wants the findings, not just the exit code. The human pass below gates.
python -m ray_tpu lint --format sarif --out "$ARTIFACT_DIR/rtlint.sarif" "$@" \
    || true
python -m ray_tpu lint "$@"

echo "rtlint gate: PASS (sarif: $ARTIFACT_DIR/rtlint.sarif)"

#!/usr/bin/env bash
# Workload flight-recorder gate: proves the per-step recorder (ISSUE 8)
# stays within its <=2% step-time budget and that the whole diagnose
# surface — StepStats aggregation, straggler detection, goodput
# buckets, serve SLO histograms, `ray_tpu diagnose` — keeps working.
#
# Two layers:
#   1. tests/test_workload.py — aggregator math under dup/replay chaos,
#      deterministic straggler naming, MFU agreement with bench.py's
#      formula, goodput sum-exactness, latency-histogram percentiles,
#      the diagnose rule set, and the live end-to-end run (train ->
#      workload series -> goodput -> /api/workload -> CLI);
#   2. the workload_recorder_overhead release entry under --smoke,
#      which enforces the smoke_criteria floors from
#      release/release_tests.yaml (paired off/on boot step rate, serve
#      burst, diagnose findings) and appends release_history.jsonl.
#
# The full-size measurement (3 boot pairs x 400 steps, <=5% gate,
# 2% budget) is the release suite proper:
#   python release/run_all.py --only workload_recorder_overhead
# Usage: ci/run_diagnose_smoke.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== workload recorder + straggler + goodput + diagnose (pytest) =="
python -m pytest tests/test_workload.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== recorder overhead + diagnose (release floors, --smoke) =="
python release/run_all.py --smoke --only workload_recorder_overhead

echo "diagnose smoke: PASS"

#!/usr/bin/env bash
# Overlap-everything gate (ISSUE 11): bucketed async gradient sync,
# quantized pipeline activations, interleaved 1F1B.
#
# Two layers, same subsystem:
#   1. tests/test_overlap.py — the functional floor (bucket partition
#      covers every leaf exactly once on odd pytrees, deterministic
#      bucket signatures, scatter/gather roundtrips, interleaved
#      schedule validity over the (S,M,v) acceptance grid + v=1
#      equivalence to plain 1F1B, comm_exposed StepStats accounting,
#      the 2-worker overlapped-sync parity run, and the quantized
#      activation-wire pipeline's convergence parity vs the exact
#      wire). These also run as part of plain tier-1
#      `pytest -m 'not slow'`.
#   2. the overlap_sync release entry under --smoke, which runs the
#      PAIRED bench.py --overlap off/on microbench and enforces
#      comm_exposed_ratio < 0.30 / trajectory parity <= 1e-6 /
#      interleaved-grid validity, appending the run to
#      release_history.jsonl.
#
# The same entry at full size: python release/run_all.py --only overlap_sync
# Usage: ci/run_overlap_bench.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== overlap (pytest, functional floor) =="
python -m pytest tests/test_overlap.py \
    -q -m 'not slow' -p no:cacheprovider "$@"

echo "== overlap (release floors, --smoke) =="
python release/run_all.py --smoke --only overlap_sync

echo "overlap bench: PASS"

#!/usr/bin/env bash
# Fast-collectives gate (ISSUE 7): quantized + topology-aware allreduce.
#
# Two layers, same subsystem:
#   1. tests/test_collective.py + tests/test_collective_quant.py — the
#      functional floor (uneven chunks, wire-dtype regression, codec
#      bounds, error-feedback drain, chaos on the DCN tier, trainer
#      backend auto-upgrade + convergence parity). These also run as
#      part of plain tier-1 `pytest -m 'not slow'`.
#   2. the collective_microbenchmark release entry under --smoke, which
#      enforces the ratio gates (quantized >=2x ring bytes/s at >=4MB,
#      hierarchical >= ring at every size, int8-wire loss parity) and
#      appends the run to release_history.jsonl.
#
# The full-size sweep (64KB -> 64MB, best-of-5) is the release suite
# proper: python release/run_all.py --only collective_microbenchmark
# Usage: ci/run_collective_bench.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== collectives (pytest, functional floor) =="
python -m pytest tests/test_collective.py tests/test_collective_quant.py \
    -q -m 'not slow' -p no:cacheprovider "$@"

echo "== collectives (release floors, --smoke) =="
python release/run_all.py --smoke --only collective_microbenchmark

echo "collective bench: PASS"

#!/usr/bin/env bash
# Telemetry-overhead gate: proves the resource-telemetry subsystem
# (ISSUE 5) stays within its <=2% task-storm budget and that the
# store/attribution/oom_risk surfaces keep working.
#
# Two layers:
#   1. tests/test_telemetry.py — tiered ring-buffer downsampling math,
#      monotonic/bounded behavior under dup/drop chaos heartbeats,
#      per-task peak-RSS attribution, the trend-aware oom_risk event,
#      and the 2-node FakeScaleCluster summary + `top` rendering;
#   2. the telemetry_overhead release entry under --smoke, which
#      enforces the smoke_criteria floors from release/
#      release_tests.yaml (paired off/on boot throughput, 2-node scale
#      scenario with >=2 tiers populated) and appends
#      release_history.jsonl.
#
# The full-size measurement (3 boot pairs x 4000 tasks, <=5% gate,
# measured ~0-2%) is the release suite proper:
#   python release/run_all.py --only telemetry_overhead
# Usage: ci/run_telemetry_overhead.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== telemetry store + attribution + oom_risk + chaos (pytest) =="
python -m pytest tests/test_telemetry.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== telemetry overhead (release floors, --smoke) =="
python release/run_all.py --smoke --only telemetry_overhead

echo "telemetry overhead: PASS"

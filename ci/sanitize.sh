#!/usr/bin/env bash
# Build + run the native test binary under ASAN and TSAN.
#
# Role-equivalent of the reference's bazel --config=asan / --config=tsan
# CI pipelines over its C++ gtest suites (SURVEY §5.2): every native
# component (epoll RPC engine, shm object store) gets exercised under both
# sanitizers on every CI run. Usage: ci/sanitize.sh [address|thread|all]
set -euo pipefail
cd "$(dirname "$0")/.."

SOURCES="src/object_store/store.cc src/rpc/transport.cc src/test/native_tests.cc"
MODES="${1:-all}"
[ "$MODES" = "all" ] && MODES="address thread"

mkdir -p build
for mode in $MODES; do
  out="build/native_tests_${mode}"
  echo "== building (${mode} sanitizer) =="
  g++ -std=c++17 -g -O1 -fsanitize="${mode}" -fno-omit-frame-pointer \
      -pthread ${SOURCES} -o "${out}"
  echo "== running (${mode} sanitizer) =="
  if [ "$mode" = "thread" ]; then
    TSAN_OPTIONS="halt_on_error=1" "./${out}"
  else
    ASAN_OPTIONS="detect_leaks=1" "./${out}"
  fi
done
echo "sanitizer suite: PASS"

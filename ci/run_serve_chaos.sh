#!/usr/bin/env bash
# Run the serve-plane chaos suite, slow scenarios included (ISSUE 13).
#
# Tier-1 CI runs `pytest -m 'not slow'`, which covers the windowed
# fail-point decision core, latency-point arming, and the ChaosMonkey
# replica kill mid-load; this script is the nightly companion that also
# executes the long windowed schedules (mid-request replica kills with
# zero lost requests, proxy kill + client failover + controller
# restart, injected slow-replica latency) plus the serve_chaos release
# benchmark in smoke mode (replica AND proxy kill under load, then an
# oom_risk-triggered drain). Usage: ci/run_serve_chaos.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== serve chaos suite (tier-1 subset) =="
python -m pytest tests/test_serve_chaos.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== serve chaos suite (slow scenarios) =="
python -m pytest tests/test_serve_chaos.py -q -m 'slow' \
    -p no:cacheprovider "$@"

echo "== serve chaos release benchmark (smoke) =="
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" RAY_TPU_RELEASE_SMOKE=1 \
    python release/benchmarks_serve_chaos.py

echo "serve chaos suite: PASS"

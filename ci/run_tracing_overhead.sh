#!/usr/bin/env bash
# Tracing-overhead gate: proves the critical-path tracing layer (ISSUE 4)
# stays cheap enough to ship enabled and FREE when disabled.
#
# Three layers:
#   1. disabled-path smoke — tests/test_tracing_chaos.py includes the
#      "tracing disabled leaves zero residue" test (no span files, no
#      context injection), plus the chaos-net JSONL-validity tests that
#      prove dup/drop RPC faults never corrupt span files or reuse ids;
#   2. tests/test_observability.py — the full-lifecycle span tree,
#      summarize_latency percentile math, timeline export, and Serve /
#      actor context propagation;
#   3. the tracing_overhead release entry under --smoke, which enforces
#      the smoke_criteria floors from release/release_tests.yaml
#      (mainline throughput with tracing off = the <=1%-vs-seed proxy;
#      paired-window enabled overhead) and appends release_history.jsonl.
#
# The full-size measurement (24 paired windows, <=15% gate, measured
# 8-10%) is the release suite proper:
#   python release/run_all.py --only tracing_overhead
# Usage: ci/run_tracing_overhead.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== tracing disabled-path + chaos smoke (pytest) =="
python -m pytest tests/test_tracing_chaos.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== observability surface (pytest) =="
python -m pytest tests/test_observability.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== tracing overhead (release floors, --smoke) =="
python release/run_all.py --smoke --only tracing_overhead

echo "tracing overhead: PASS"

#!/usr/bin/env bash
# Run the hang-doctor suite (ISSUE 14).
#
# Tier-1 CI runs `pytest -m 'not slow'`, which already covers the
# flight-ring units, the adaptive-deadline watchdog, the evidence-merge
# report builder, the span<->flight join, the recorder-bypass lint
# rule, and both chaos e2e scenarios (one delayed rank is named; a
# uniformly-slow cluster stays silent). This script is the nightly
# companion that re-runs that subset and then executes the hang_doctor
# release benchmark in smoke mode, enforcing the acceptance gates
# (stall_detected==1, named_rank_correct==1, false_positives==0,
# recorder_overhead<=0.02) via release/run_all.py.
# Usage: ci/run_hang_doctor.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== hang doctor suite (unit + chaos e2e) =="
python -m pytest tests/test_hang_doctor.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== span<->flight join + recorder-bypass lint regressions =="
python -m pytest tests/test_observability.py -q -k 'join_flight' \
    -p no:cacheprovider "$@"
python -m pytest tests/test_lint.py -q -k 'comm_recorder' \
    -p no:cacheprovider "$@"

echo "== hang doctor release benchmark (smoke, gated) =="
python release/run_all.py --smoke --only hang_doctor

echo "hang doctor suite: PASS"

#!/usr/bin/env bash
# Control-plane scale smoke: the downsized scale envelope (8 fake nodes /
# 200 actors / 20 placement groups / 5k leases) on the in-process
# FakeScaleCluster, sized to finish well inside the tier-1 timeout.
#
# Two layers, same envelope:
#   1. tests/test_scale_smoke.py — fast non-slow pytest markers (these
#      also run as part of plain tier-1 `pytest -m 'not slow'`), including
#      the seeded dup/drop mutation-idempotency burst;
#   2. the four scale_* release entries under --smoke, which enforce the
#      smoke_criteria floors from release/release_tests.yaml and append
#      the run to release_history.jsonl.
#
# The full-size envelope (32 nodes / 2k actors / 200 pgs / 100k leases)
# is the release suite proper: python release/run_all.py --only scale_...
# Usage: ci/run_scale_smoke.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== scale smoke (pytest, downsized envelope) =="
python -m pytest tests/test_scale_smoke.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== scale smoke (release floors, --smoke) =="
for name in scale_nodes_32 scale_actors_2000 scale_pgs_200 scale_tasks_100k; do
    python release/run_all.py --smoke --only "$name"
done

echo "scale smoke: PASS"

#!/usr/bin/env bash
# Run the elastic-training / checkpoint-commit suite under churn.
#
# Tier-1 CI already runs these modules without markers; this script is
# the nightly companion for the elasticity work (ISSUE 6): the
# two-phase commit protocol (including the mid-save kill fail-point),
# resume-exact ingest parity at equal and shrunken world sizes, the
# grow-back capacity probe, and the oom_risk preemptive drain.
# Usage: ci/run_elastic_chaos.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== checkpoint commit protocol + resume-exact ingest =="
python -m pytest tests/test_checkpoint_commit.py -q \
    -p no:cacheprovider "$@"

echo "== elasticity: step-down, grow-back, oom_risk drain =="
python -m pytest tests/test_train_elastic.py -q \
    -p no:cacheprovider "$@"

echo "elastic chaos suite: PASS"

#!/usr/bin/env bash
# Run the self-healing rtdag suite (ISSUE 16).
#
# Tier-1 CI runs `pytest -m 'not slow'`, which already covers the
# supervised kill-mid-stream exactly-once e2e, snapshot/restore resume,
# unsupervised failure-path cleanup + edge-evidence errors, shm epoch
# fencing, and the slow-wire no-false-restart chaos test. This script
# is the nightly companion that re-runs that subset plus the PR-15
# chaos e2e (typed death + hang doctor), re-certifies the epoch-fenced
# DAG wires in the static comm graph, and executes the
# dag_chaos_recovery release benchmark in smoke mode, enforcing the
# acceptance gates (lost_outputs==0, dup_outputs==0, recoveries==1,
# bounded recovery_latency_s, dag_controller_rpcs==0, bounded
# supervise_overhead_pct) via release/run_all.py.
# Usage: ci/run_dag_recovery.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== DAG recovery suite (supervisor + epoch fencing + replay) =="
python -m pytest tests/test_dag_recovery.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== DAG chaos e2e (typed death + hang doctor) =="
python -m pytest tests/test_dag_chaos.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== commgraph certifies epoch-fenced DAG wires =="
python -m ray_tpu lint --comm-graph

echo "== DAG chaos-recovery release benchmark (smoke, gated) =="
python release/run_all.py --smoke --only dag_chaos_recovery

echo "DAG recovery suite: PASS"

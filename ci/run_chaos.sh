#!/usr/bin/env bash
# Run the full chaos/fault-injection suite, slow scenarios included.
#
# Tier-1 CI runs `pytest -m 'not slow'`, which covers the seeded <60s
# smoke scenario; this script is the nightly/occasional companion that
# also executes the long schedules (worker kill + 10s asymmetric
# partition, partition-then-heal re-registration, typed replica-death
# errors). Usage: ci/run_chaos.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== chaos suite (tier-1 subset) =="
python -m pytest tests/test_chaos.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== chaos suite (slow scenarios) =="
python -m pytest tests/test_chaos.py -q -m 'slow' \
    -p no:cacheprovider "$@"

echo "chaos suite: PASS"

#!/usr/bin/env bash
# Run the cluster-step-profiler suite (ISSUE 20).
#
# Tier-1 CI runs `pytest -m 'not slow'`, which covers the
# capture-plane units (step-boundary alignment, typed errors, the
# armed-timer leak guard), host-sampler robustness (threads exiting
# mid-capture, dead-tid eviction), merge determinism, the fwd/bwd/opt
# split clamping, and the dashboard profile routes. This script is the
# nightly companion: it re-runs the whole file INCLUDING the slow-marked
# chaos e2e scenarios (CLI capture merges two step-aligned ranks; a
# dragged rank auto-triggers a capture naming its hot phase; the
# uniform twin stays silent), then executes the step_profiler release
# benchmark in smoke mode, enforcing the acceptance gates
# (idle_overhead<=0.01, capture_overhead<=0.05, named_rank_correct==1,
# false_positives==0) via release/run_all.py.
# Usage: ci/run_profile_smoke.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== step profiler suite (unit + chaos e2e) =="
python -m pytest tests/test_profiler.py -q \
    -p no:cacheprovider "$@"

echo "== dashboard profile routes =="
python -m pytest tests/test_platform.py -q -k 'profile' \
    -p no:cacheprovider "$@"

echo "== step profiler release benchmark (smoke, gated) =="
python release/run_all.py --smoke --only step_profiler

echo "step profiler suite: PASS"

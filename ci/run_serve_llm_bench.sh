#!/usr/bin/env bash
# Run the serve-LLM suite, slow scenarios included (ISSUE 17).
#
# Tier-1 CI runs `pytest -m 'not slow'`, which covers the hash-ring
# stability properties, slot/KV-pool unit behavior, the quantized KV
# wire + epoch fencing, engine continuous batching (admission overlap,
# deadline eviction, fast shed, fence dedup), multiplex pin-before-
# evict, the kv-headroom autoscaling floor, and the in-cluster e2e
# paths (unary/stream/batch, zero-controller-RPC steady state, batch-
# full fast 503). This script is the nightly companion that also runs
# the long windowed schedule (mid-stream decode replica kill with
# exactly-once token delivery) plus the serve_llm release benchmark in
# smoke mode (throughput + replica AND proxy kill under load + the
# independent pool-scaling phase).
# Usage: ci/run_serve_llm_bench.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== serve llm suite (tier-1 subset) =="
python -m pytest tests/test_serve_llm.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== serve llm suite (slow scenarios) =="
python -m pytest tests/test_serve_llm.py -q -m 'slow' \
    -p no:cacheprovider "$@"

echo "== serve llm release benchmark (smoke) =="
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" RAY_TPU_RELEASE_SMOKE=1 \
    python release/benchmarks_serve_llm.py

echo "serve llm suite: PASS"

#!/usr/bin/env bash
# GSPMD sharded-training gate (ISSUE 10): NamedSharding mesh trainer +
# MPMD pipeline stages.
#
# Two layers, same subsystem:
#   1. tests/test_sharding.py — the functional floor (mesh-spec edge
#      cases, FSDP auto-policy divisibility fallbacks, 1F1B schedule
#      ordering/bubble, dp8 vs dp2xfsdp2xtp2 loss parity, the
#      replicated path refusing over-budget states, and the elastic
#      resize dp=4 -> dp=2xfsdp=2 bitwise loss-trajectory parity).
#      These also run as part of plain tier-1 `pytest -m 'not slow'`.
#   2. the sharded_training release entry under --smoke, which enforces
#      fit-at-1B / replicated-refuses / pipeline-bubble <= 0.25 /
#      MFU >= 0.72-on-chip and appends the run to release_history.jsonl.
#
# The same entry at full size: python release/run_all.py --only sharded_training
# Usage: ci/run_sharded_bench.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== sharding (pytest, functional floor) =="
python -m pytest tests/test_sharding.py \
    -q -m 'not slow' -p no:cacheprovider "$@"

echo "== sharding (release floors, --smoke) =="
python release/run_all.py --smoke --only sharded_training

echo "sharded bench: PASS"

#!/usr/bin/env bash
# Serve-LLM observability gate (ISSUE 19): proves the token-level
# observability plane — per-sequence trace continuity through the
# channel families, the exact-sum token ledger, TTFT/TPOT histograms,
# and the Perfetto sequence export — costs <=2% decode throughput when
# fully sampled and stays control-plane silent.
#
# Three layers:
#   1. tests/test_seq_observability.py — ctx wire roundtrip, sampling
#      determinism, ledger exact-sum + replay dedup vs a fenced fake
#      mailbox, engine timeline/kv export, the diagnose SLO + KV-trend
#      rules, the Perfetto builder, and the end-to-end single-trace-id
#      tests (proxy -> prefill -> KV wire -> decode -> every token);
#   2. tests/test_observability.py — includes the dag-side join test
#      (channel trace ids landing in flight records at site="dag");
#   3. the serve_llm_observability release entry under --smoke: paired
#      off/on decode windows gate sampled overhead <=2%, and the
#      steady_rpc_probe re-run with tracing+sampling enabled gates
#      decode_controller_rpcs==0; appends release_history.jsonl.
#
# The full-size measurement (24 paired windows) is the release suite
# proper:
#   python release/run_all.py --only serve_llm_observability
# Usage: ci/run_seq_tracing_overhead.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== serve-LLM sequence observability (pytest) =="
python -m pytest tests/test_seq_observability.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== dataflow trace joins (pytest) =="
python -m pytest tests/test_observability.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "== sampled observability overhead (release floors, --smoke) =="
python release/run_all.py --smoke --only serve_llm_observability

echo "serve-LLM observability overhead: PASS"
